"""Serving observability tests (``docs/observability.md``): span
tracing, the flight recorder, histogram metrics and the debug
endpoints.

The acceptance contract: with ``serving.tracing`` OFF, serving outputs
and executable counts are identical to pre-observability behavior; with
it ON, greedy outputs stay bitwise-identical, ``dump_trace()`` emits
valid Chrome trace-event JSON holding one complete span tree per
request in a mixed 7-request/3-slot run, and every ``RequestResult``
carries a queue/prefill/host/decode latency breakdown that sums to the
measured wall total.  A breaker-open and a ``DrainTimeout`` each
produce a flight-recorder dump whose tail reconstructs the failing
dispatch sequence.  ``/metrics`` exposes TTFT / TBT / queue-wait /
dispatch-duration / lock-wait histograms that survive a text-format
round trip (with hostile label values), and TTFT/TBT stamps ride an
injectable clock and are never re-stamped by a late-attached
``TokenStream`` replay.

Deliberately the SMALLEST serving model in the suite (1 layer, hidden
32): every assertion here is about HOST bookkeeping, so the device
program only needs to exist — tier-1 runs under a hard wall-clock cap
and every serve() compiles a fresh program trio."""

import http.client
import json
import os
import re
import signal
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.inference.serving.slo import DrainTimeout
from deepspeed_tpu.models.transformer import Transformer, TransformerConfig

SERVING = {"enabled": True, "num_slots": 3, "max_cache_len": 64,
           "prefill_chunk": 8, "prefill_token_budget": 16,
           "decode_block": 2}


@pytest.fixture(scope="module")
def shared_engine():
    model = Transformer(TransformerConfig(
        vocab_size=61, hidden_size=32, num_layers=1, num_heads=2,
        max_seq_len=64, use_flash_attention=False, dtype="float32"))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 61, (2, 12)),
                      jnp.int32)
    params = model.init(jax.random.key(0), {"input_ids": ids})
    eng = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "prefill_chunk_size": 8,
                       "serving": SERVING})
    eng.set_params(params)
    return eng


def _workload(rng, n=7):
    prompts = [rng.integers(1, 61, (int(p),)).astype(np.int32)
               for p in rng.integers(9, 21, (n,))]
    news = [int(x) for x in rng.integers(3, 9, (n,))]
    return prompts, news


# --------------------------------------------------------------------- #
# Tracing on/off: bitwise outputs, zero new executables, span trees,
# latency breakdown
# --------------------------------------------------------------------- #
def test_tracing_off_on_bitwise_zero_new_execs_spans_breakdown(
        shared_engine, tmp_path):
    """The acceptance proof, one engine, two servers: the SAME mixed
    7-request/3-slot workload with tracing off and on — outputs
    bitwise-equal (the off-run's equality to solo generate() is
    test_serving.py's own proof), the same executable count minted by
    both servers (observability adds zero programs), dump_trace() holds
    one complete span tree per request, and the RequestResult breakdown
    sums exactly to latency_s."""
    eng = shared_engine
    rng = np.random.default_rng(7)
    prompts, news = _workload(rng)

    srv_off = eng.serve()
    n_aot_0 = len(eng._aot)
    rids = [srv_off.submit(p, max_new_tokens=n)
            for p, n in zip(prompts, news)]
    outs_off = srv_off.drain()
    execs_off = len(eng._aot) - n_aot_0
    assert srv_off.histograms() is None
    assert srv_off.flightrec_snapshot() is None
    # tracing off: breakdown fields stay None (seed behavior)
    res_off = srv_off.result(rids[0])
    assert res_off.queue_s is None and res_off.latency_s is None
    with pytest.raises(RuntimeError, match="serving.tracing is off"):
        srv_off.dump_trace(str(tmp_path / "no.json"))
    with pytest.raises(RuntimeError, match="flight_recorder is off"):
        srv_off.dump_flightrec()
    srv_off.close()

    srv = eng.serve(tracing=True, flight_recorder=True,
                    flight_recorder_dir=str(tmp_path))
    n_aot_1 = len(eng._aot)
    rids_on = [srv.submit(p, max_new_tokens=n)
               for p, n in zip(prompts, news)]
    outs_on = srv.drain()
    execs_on = len(eng._aot) - n_aot_1
    # zero-new-executables, extended over the observability layer:
    # every server compiles its own decode/admit/chunk trio (fresh fn
    # identities per serve()), and the tracing server minted EXACTLY
    # the same count — observability adds no program
    assert execs_on == execs_off, (execs_off, execs_on)
    n_decode = sum(1 for sig in eng._aot
                   if sig and sig[0] == id(srv._decode_fn))
    assert n_decode == 1, n_decode
    for r_off, r_on in zip(rids, rids_on):
        np.testing.assert_array_equal(
            outs_off[r_off], outs_on[r_on],
            err_msg="tracing changed serving outputs")

    # ---- latency breakdown sums exactly to the measured wall total
    for rid in rids_on:
        res = srv.result(rid)
        parts = (res.queue_s, res.prefill_s, res.host_s, res.decode_s)
        assert all(p is not None and p >= 0 for p in parts), res
        assert res.latency_s > 0
        assert abs(sum(parts) - res.latency_s) < 1e-9, (parts,
                                                        res.latency_s)
        assert res.ttft_s is not None

    # ---- Chrome trace export: valid JSON, one span tree per request
    path = srv.dump_trace(str(tmp_path / "trace.json"))
    with open(path) as f:
        trace = json.load(f)
    evs = trace["traceEvents"]
    assert trace["otherData"]["dropped"] == 0
    tracks = {e["args"]["name"] for e in evs if e.get("ph") == "M"}
    # one track per slot plus the scheduler/queue/handler threads
    assert {"scheduler", "queue", "handler"} <= tracks, tracks
    assert {f"slot {s}" for s in range(srv.num_slots)} <= tracks, tracks
    for e in evs:
        assert e["ph"] in ("X", "M", "i"), e
        if e["ph"] == "X":
            assert isinstance(e["ts"], (int, float)) \
                and isinstance(e["dur"], (int, float)) and e["dur"] >= 0
    by_rid = {}
    for e in evs:
        a = e.get("args", {})
        if e.get("ph") == "X" and "rid" in a:
            by_rid.setdefault(a["rid"], set()).add(e["name"])
    for rid in rids_on:
        assert {"request", "queue", "prefill", "decode"} <= by_rid[rid], \
            (rid, by_rid.get(rid))
    # commit markers carry tokens-committed counts at the mirror drain
    commits = [e for e in evs if e["name"] == "commit"]
    assert commits and all("tokens" in e["args"] for e in commits)
    assert sum(e["args"]["tokens"] for e in commits) \
        == srv.stats["decode_tokens"]

    # ---- histograms observed the run
    h = srv.histograms()
    assert h.ttft.count == len(rids_on)
    assert h.queue_wait.count == len(rids_on)
    assert h.tbt.count == srv.stats["decode_tokens"]
    assert set(h.dispatch._children) >= {"decode", "admit",
                                         "prefill_chunk"}

    # ---- flight recorder saw the whole story
    snap = srv.flightrec_snapshot()
    kinds = {e["ev"] for e in snap["events"]}
    assert {"submit", "admit_start", "dispatch_begin", "dispatch_end",
            "commit", "terminal"} <= kinds, kinds
    srv.close()


# --------------------------------------------------------------------- #
# Flight-recorder auto-dumps: breaker-open and DrainTimeout
# --------------------------------------------------------------------- #
def test_flightrec_dump_on_breaker_open(shared_engine, tmp_path):
    """Two consecutive dispatch failures trip the breaker; the dump
    lands on disk and its tail reconstructs the failing dispatch
    sequence (dispatch_begin -> dispatch_error -> breaker_open)."""
    eng = shared_engine
    rng = np.random.default_rng(23)
    prompts, _ = _workload(rng, n=2)
    srv = eng.serve(num_slots=2, breaker_threshold=2,
                    breaker_cooldown_s=30.0, flight_recorder=True,
                    flight_recorder_dir=str(tmp_path / "fr"))
    for p in prompts:
        srv.submit(p, max_new_tokens=4)

    real_run = eng._run_guarded

    def failing_run(fn, args):
        raise RuntimeError("injected sick-device dispatch failure")

    eng._run_guarded = failing_run
    try:
        srv.step()                       # failure 1 — absorbed
        assert srv._flightrec.last_dump_path is None
        srv.step()                       # failure 2 — breaker OPENS
    finally:
        eng._run_guarded = real_run
    assert srv._breaker.open
    dump_path = srv._flightrec.last_dump_path
    assert dump_path is not None and os.path.exists(dump_path)
    with open(dump_path) as f:
        dump = json.load(f)
    assert dump["reason"] == "breaker_open"
    tail = [e["ev"] for e in dump["events"]]
    # the last events tell the failure story in order
    i_begin = max(i for i, e in enumerate(tail) if e == "dispatch_begin")
    i_err = max(i for i, e in enumerate(tail) if e == "dispatch_error")
    i_open = tail.index("breaker_open")
    assert i_begin < i_err < i_open == len(tail) - 1, tail[-8:]
    errs = [e for e in dump["events"] if e["ev"] == "dispatch_error"]
    assert all("sick-device" in e["error"] for e in errs)
    assert all("seq" in e and "t_mono" in e and "t_wall" in e
               for e in dump["events"])
    srv.close()


def test_flightrec_dump_on_drain_timeout(shared_engine, tmp_path):
    eng = shared_engine
    rng = np.random.default_rng(29)
    prompts, _ = _workload(rng, n=1)
    srv = eng.serve(num_slots=2, flight_recorder=True,
                    flight_recorder_dir=str(tmp_path / "fr2"))
    r1 = srv.submit(prompts[0], max_new_tokens=30)
    while srv.active_slots == 0:
        srv.step()
    srv._dispatch_decode = lambda: False          # wedge the scheduler
    with pytest.raises(DrainTimeout):
        srv.drain(timeout_s=0.2)
    dump_path = srv._flightrec.last_dump_path
    assert dump_path is not None and os.path.exists(dump_path)
    with open(dump_path) as f:
        dump = json.load(f)
    assert dump["reason"] == "drain_timeout"
    kinds = [e["ev"] for e in dump["events"]]
    assert kinds[-1] == "drain_timeout"
    # the ring holds the request's real dispatch history before the
    # wedge — the sequence a point-in-time diagnostic cannot show
    assert "dispatch_end" in kinds and "admit_start" in kinds
    assert f"request {r1}" in dump["events"][-1]["diag"]
    srv.close()


# --------------------------------------------------------------------- #
# Injected clock: TTFT/TBT determinism + replay never re-stamps
# --------------------------------------------------------------------- #
def test_ttft_tbt_injected_clock_and_replay_no_restamp(shared_engine):
    """The tracer's clock is injectable: all TTFT/TBT observations are
    exact multiples of the fake tick, proving the histograms ride the
    injected clock; a late-attached TokenStream replay (which re-reads
    the token record) leaves every histogram bit-identical — replayed
    events never re-stamp timestamps."""
    eng = shared_engine
    rng = np.random.default_rng(31)
    prompts, _ = _workload(rng, n=2)
    news = [4, 5]
    srv = eng.serve(tracing=True)
    tick = [0.0]

    def fake_clock():
        tick[0] += 0.125
        return tick[0]

    srv._tracer._clock = fake_clock
    rids = [srv.submit(p, max_new_tokens=n)
            for p, n in zip(prompts, news)]
    srv.drain()
    h = srv.histograms()
    assert h.ttft.count == 2
    assert h.tbt.count == sum(news) - len(news)
    for hist in (h.ttft, h.tbt, h.queue_wait):
        snap = hist.snapshot()
        scaled = snap["sum"] / 0.125
        assert abs(scaled - round(scaled)) < 1e-6, \
            "histogram stamps did not come from the injected clock"
    before = {k: getattr(h, k).snapshot()
              for k in ("ttft", "tbt", "queue_wait")}

    # late attach: full replay of every token + the end event
    for rid, n in zip(rids, news):
        toks, end = srv.token_events(rid).tokens(timeout=5)
        assert len(toks) == n and end["status"] == "COMPLETED"
    after = {k: getattr(h, k).snapshot()
             for k in ("ttft", "tbt", "queue_wait")}
    assert after == before, "TokenStream replay re-stamped timestamps"
    srv.close()


# --------------------------------------------------------------------- #
# /metrics round trip (HELP/TYPE everywhere, escaping, histograms) +
# the gated-off debug endpoints on the same frontend
# --------------------------------------------------------------------- #
_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? (-?[0-9.eE+-]+|\+Inf|NaN)$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v):
    out, i = [], 0
    while i < len(v):
        if v[i] == "\\" and i + 1 < len(v):
            out.append({"n": "\n", '"': '"', "\\": "\\"}.get(
                v[i + 1], v[i + 1]))
            i += 2
        else:
            out.append(v[i])
            i += 1
    return "".join(out)


def parse_prometheus(text):
    """Minimal Prometheus text-format parser: returns (types, helps,
    samples) with samples = [(name, labels_dict, value)].  Raises on
    any line that is neither a comment nor a well-formed sample."""
    types, helps, samples = {}, {}, []
    for line in text.rstrip("\n").split("\n"):
        if line.startswith("# HELP "):
            name = line.split(" ", 3)[2]
            helps[name] = line.split(" ", 3)[3]
        elif line.startswith("# TYPE "):
            _, _, name, typ = line.split(" ", 3)
            types[name] = typ.strip()
        elif line.startswith("#"):
            continue
        else:
            m = _SAMPLE_RE.match(line)
            assert m, f"malformed exposition line: {line!r}"
            labels = {k: _unescape(v)
                      for k, v in _LABEL_RE.findall(m.group(2) or "")}
            samples.append((m.group(1), labels, float(m.group(3))))
    return types, helps, samples


def _family(name, types):
    if name in types:
        return name
    for suf in ("_bucket", "_sum", "_count"):
        if name.endswith(suf) and name[:-len(suf)] in types:
            return name[:-len(suf)]
    return None


def _get(port, path, method="GET"):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request(method, path)
    resp = conn.getresponse()
    body = resp.read()
    conn.close()
    return resp.status, body


NASTY_CLIENT = 'we"ird\\ten\nant-{x="1"}'


def test_metrics_round_trip_histograms_escaping_and_gating(
        shared_engine):
    eng = shared_engine
    srv = eng.serve(tracing=True, fairness_tokens_per_s=10000.0)
    from deepspeed_tpu.inference.serving.frontend import \
        ServingHTTPFrontend
    rng = np.random.default_rng(37)
    prompts, _ = _workload(rng, n=2)
    with ServingHTTPFrontend(srv) as fe:
        for k, p in enumerate(prompts):
            conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                              timeout=180)
            conn.request("POST", "/v1/generate", json.dumps(
                {"input_ids": [int(t) for t in p], "max_new_tokens": 4,
                 "client_id": NASTY_CLIENT if k == 0 else "plain"}))
            assert conn.getresponse().status == 200
            conn.close()
        conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                          timeout=60)
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        assert resp.status == 200
        body = resp.read().decode()
        conn.close()
        # this server has tracing but NO flight recorder and NO profile
        # endpoint: the debug routes answer 404-with-reason
        status, b = _get(fe.port, "/debug/flightrec")
        assert status == 404 and b"flight recorder disabled" in b
        status, b = _get(fe.port, "/debug/profile?secs=1", "POST")
        assert status == 404 and b"profiling endpoint disabled" in b
    srv.close()

    types, helps, samples = parse_prometheus(body)
    # exposition correctness: every sample belongs to a family with
    # BOTH # TYPE and # HELP
    for name, labels, value in samples:
        fam = _family(name, types)
        assert fam is not None, f"sample {name} has no # TYPE"
        assert fam in helps, f"sample {name} has no # HELP"
    # hostile label value round-trips exactly
    fairness = [(la, v) for n, la, v in samples
                if n == "dstpu_serving_fairness_window_tokens"]
    assert any(la.get("client") == NASTY_CLIENT for la, _ in fairness), \
        fairness
    # the five histogram families, each parsing as a real histogram
    for fam in ("dstpu_serving_ttft_seconds",
                "dstpu_serving_tbt_seconds",
                "dstpu_serving_queue_wait_seconds",
                "dstpu_serving_dispatch_seconds",
                "dstpu_serving_lock_acquire_wait_seconds"):
        assert types.get(fam) == "histogram", (fam, types.get(fam))
        rows = [(la, v) for n, la, v in samples
                if n == f"{fam}_bucket"]
        assert rows, fam
        # cumulative counts are monotone in le, per label subset
        keysets = {tuple(sorted((k, v) for k, v in la.items()
                                if k != "le")) for la, _ in rows}
        for ks in keysets:
            sub = [(la["le"], v) for la, v in rows
                   if tuple(sorted((k, v2) for k, v2 in la.items()
                            if k != "le")) == ks]
            fin = sorted([(float(le), v) for le, v in sub
                          if le != "+Inf"])
            counts = [v for _, v in fin]
            assert counts == sorted(counts), (fam, ks, fin)
            inf = [v for le, v in sub if le == "+Inf"]
            cnt = [v for n, la, v in samples
                   if n == f"{fam}_count"
                   and tuple(sorted((k, v2) for k, v2 in la.items())) == ks]
            assert inf == cnt, (fam, ks, inf, cnt)
    # TTFT histogram actually measured the run
    ttft_count = [v for n, la, v in samples
                  if n == "dstpu_serving_ttft_seconds_count"]
    assert ttft_count == [float(len(prompts))], ttft_count


# --------------------------------------------------------------------- #
# Debug endpoints live: /debug/flightrec, /debug/profile, SIGUSR2
# --------------------------------------------------------------------- #
def test_debug_flightrec_profile_and_sigusr2(shared_engine, tmp_path):
    from deepspeed_tpu.inference.serving.frontend import \
        ServingHTTPFrontend
    eng = shared_engine
    rng = np.random.default_rng(41)
    prompts, _ = _workload(rng, n=1)
    srv = eng.serve(flight_recorder=True,
                    flight_recorder_dir=str(tmp_path / "fr3"),
                    profile_endpoint=True)
    fe = ServingHTTPFrontend(srv).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                          timeout=180)
        conn.request("POST", "/v1/generate", json.dumps(
            {"input_ids": [int(t) for t in prompts[0]],
             "max_new_tokens": 3}))
        assert conn.getresponse().status == 200
        conn.close()
        status, body = _get(fe.port, "/debug/flightrec")
        assert status == 200
        snap = json.loads(body)
        assert snap["recorded"] >= len(snap["events"]) > 0
        assert {"submit", "terminal"} <= {e["ev"] for e in snap["events"]}

        status, body = _get(fe.port, "/debug/profile?secs=0", "POST")
        assert status == 200, body
        prof = json.loads(body)
        assert os.path.isdir(prof["trace_dir"])
        status, body = _get(fe.port, "/debug/profile?secs=abc", "POST")
        assert status == 400

        # SIGUSR2 -> ring dump, engine lock never taken
        if threading.current_thread() is threading.main_thread():
            fe.install_flightrec_signal_handler()
            os.kill(os.getpid(), signal.SIGUSR2)
            for _ in range(100):
                if srv._flightrec.last_dump_path:
                    break
                time.sleep(0.05)
            assert srv._flightrec.last_dump_path \
                and os.path.exists(srv._flightrec.last_dump_path)
            with open(srv._flightrec.last_dump_path) as f:
                assert json.load(f)["reason"] == "sigusr2"
    finally:
        fe.shutdown()                    # restores signal handlers
        srv.close()
