"""MoE tests — analog of reference ``tests/unit/moe/test_moe.py``: gating
invariants, dispatch/combine round-trip, EP sharding, end-to-end training."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import flax.linen as nn

import deepspeed_tpu
from deepspeed_tpu.moe.sharded_moe import (top1gating, topkgating,
                                           moe_dispatch_combine)
from deepspeed_tpu.moe.layer import MoE


def test_top1_gating_shapes_and_capacity():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.standard_normal((32, 4)).astype(np.float32))
    aux, combine, dispatch, counts = top1gating(logits, capacity_factor=1.0,
                                                min_capacity=4)
    T, E, C = combine.shape
    assert (T, E) == (32, 4) and C == 8
    # each token goes to at most one (expert, slot)
    assert np.all(np.asarray(jnp.sum(dispatch, axis=(1, 2))) <= 1)
    # no slot used twice
    assert np.all(np.asarray(jnp.sum(dispatch, axis=0)) <= 1)
    assert float(aux) > 0


def test_top1_capacity_drops_overflow():
    # all tokens prefer expert 0 → only C survive
    logits = jnp.tile(jnp.asarray([[10.0, 0.0]]), (16, 1))
    aux, combine, dispatch, counts = top1gating(logits, capacity_factor=1.0,
                                                min_capacity=1)
    C = combine.shape[2]
    kept = int(jnp.sum(dispatch))
    assert kept == C, f"capacity {C} but kept {kept}"


def test_topk_gating_normalized():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.standard_normal((16, 4)).astype(np.float32))
    aux, combine, dispatch, counts = topkgating(logits, k=2,
                                                capacity_factor=2.0)
    # combine weights per token sum to ~1 when nothing dropped
    sums = np.asarray(jnp.sum(combine, axis=(1, 2)))
    np.testing.assert_allclose(sums, np.ones(16), atol=1e-5)
    # each token hits exactly 2 experts
    per_tok = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
    assert np.all(per_tok == 2)


def test_dispatch_combine_identity():
    """With identity experts and top-1 no-drop, y == gate * x."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((8, 16)).astype(np.float32))
    logits = jnp.asarray(rng.standard_normal((8, 4)).astype(np.float32))
    aux, combine, dispatch, _ = top1gating(logits, capacity_factor=4.0,
                                           min_capacity=8)
    y = moe_dispatch_combine(x, combine, dispatch, lambda e: e)
    gates = np.asarray(jax.nn.softmax(logits, -1).max(-1))
    np.testing.assert_allclose(np.asarray(y), np.asarray(x) * gates[:, None],
                               atol=1e-5, rtol=1e-5)


class MoEModel(nn.Module):
    num_experts: int = 4
    ep_size: int = 1

    @nn.compact
    def __call__(self, batch):
        x, y = batch["x"], batch["y"]
        h = nn.Dense(32)(x)
        h2, aux, _ = MoE(hidden_size=32, num_experts=self.num_experts,
                         ep_size=self.ep_size, k=1, capacity_factor=2.0,
                         dtype=jnp.float32, name="moe")(h)
        h = h + h2
        logits = nn.Dense(8)(h)
        oh = jax.nn.one_hot(y, 8)
        ce = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * oh, -1))
        return ce + 0.01 * aux


def moe_batch(bs=16, seed=0):
    rng = np.random.default_rng(seed)
    return {"x": rng.standard_normal((bs, 16)).astype(np.float32),
            "y": rng.integers(0, 8, (bs,)).astype(np.int32)}


def test_moe_model_trains_with_engine_ep():
    engine, *_ = deepspeed_tpu.initialize(
        model=MoEModel(num_experts=4, ep_size=4),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
                "moe": {"ep_size": 4},
                "zero_optimization": {"stage": 1}})
    assert engine.topology.ep == 4
    losses = []
    for i in range(8):
        loss = engine(moe_batch(seed=0))
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0]
    # expert params sharded over ep
    leaves = jax.tree_util.tree_leaves_with_path(engine.params)
    expert_leaves = [(p, l) for p, l in leaves if "experts" in str(p).lower()]
    assert expert_leaves
    assert any("ep" in str(l.sharding.spec) for _, l in expert_leaves), \
        "expert params not sharded over ep axis"


def test_moe_residual():
    model = MoEModel(num_experts=2)
    batch = moe_batch()
    params = model.init(jax.random.key(0), batch)
    loss = model.apply(params, batch)
    assert np.isfinite(float(loss))


def test_moe_transformer_trunk_trains():
    """MoE in the flagship Transformer trunk (every 2nd block swaps MLP →
    MoE; Megatron-DeepSpeed MoE-GPT layout): trains under the engine with
    experts sharded over ep, aux loss folded into the objective, and decode
    still works."""
    from deepspeed_tpu.models.transformer import (Transformer,
                                                  TransformerConfig)
    cfg = TransformerConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        max_seq_len=16, dtype="float32", use_flash_attention=False,
        remat=True, scan_layers=False, moe_num_experts=4, moe_every=2,
        moe_ep_size=4, moe_capacity_factor=2.0)   # remat on: the train
    # bool must stay static through jax.checkpoint (static_argnums)
    engine, *_ = deepspeed_tpu.initialize(
        model=Transformer(cfg),
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
                "moe": {"ep_size": 4},
                "zero_optimization": {"stage": 1}})
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 64, (8, 16)).astype(np.int32)
    losses = []
    for _ in range(10):
        loss = engine({"input_ids": ids})
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0], losses

    # expert params exist only in odd blocks and shard over ep
    leaves = jax.tree_util.tree_leaves_with_path(engine.params)
    expert = [(str(p), l) for p, l in leaves if "experts" in str(p).lower()]
    assert expert and all("layers_1" in p for p, _ in expert), \
        [p for p, _ in expert]
    assert any("ep" in str(l.sharding.spec) for _, l in expert)

    # scan_layers must be rejected with MoE
    with pytest.raises(ValueError):
        TransformerConfig(vocab_size=64, hidden_size=32, num_layers=2,
                          num_heads=4, moe_num_experts=4, scan_layers=True)


def test_moe_trunk_checkpoint_roundtrip(tmp_path):
    """MoE checkpoint save/load (reference
    ``tests/unit/checkpoint/test_moe_checkpoint.py``): ep-sharded expert
    params must survive an engine save/load round trip bit-exactly and
    come back with their ep sharding."""
    from deepspeed_tpu.models.transformer import (Transformer,
                                                  TransformerConfig)
    cfg = TransformerConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        max_seq_len=16, dtype="float32", use_flash_attention=False,
        remat=False, scan_layers=False, moe_num_experts=4, moe_every=2,
        moe_ep_size=4, moe_capacity_factor=2.0)
    conf = {"train_micro_batch_size_per_gpu": 4,
            "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
            "moe": {"ep_size": 4},
            "zero_optimization": {"stage": 1}}
    engine, *_ = deepspeed_tpu.initialize(model=Transformer(cfg), config=conf)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 64, (8, 16)).astype(np.int32)
    for _ in range(3):
        loss = engine({"input_ids": ids})
        engine.backward(loss)
        engine.step()
    engine.save_checkpoint(str(tmp_path))
    before = jax.device_get(engine.params)

    engine2, *_ = deepspeed_tpu.initialize(model=Transformer(cfg), config=conf)
    engine2.load_checkpoint(str(tmp_path))
    after = jax.device_get(engine2.params)
    jax.tree.map(np.testing.assert_array_equal, before, after)
    assert engine2.global_steps == engine.global_steps
    leaves = jax.tree_util.tree_leaves_with_path(engine2.params)
    expert = [l for p, l in leaves if "experts" in str(p).lower()]
    assert expert and any("ep" in str(l.sharding.spec) for l in expert)


def test_moe_checkpoint_across_ep_sizes(tmp_path):
    """Elastic expert-parallel resize (the reference's
    ``test_moe_checkpoint.py`` cross-ep_size cases): a checkpoint saved at
    ep=4 loads into an ep=2 engine — expert-stacked params reshard onto the
    new topology (ep sharding asserted) and training continues."""
    from deepspeed_tpu.models.transformer import (Transformer,
                                                  TransformerConfig)

    def make(ep):
        cfg = TransformerConfig(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
            max_seq_len=16, dtype="float32", use_flash_attention=False,
            remat=False, scan_layers=False, moe_num_experts=4, moe_every=2,
            moe_ep_size=ep, moe_capacity_factor=2.0)
        conf = {"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
                "moe": {"ep_size": ep},
                "zero_optimization": {"stage": 1}}
        engine, *_ = deepspeed_tpu.initialize(model=Transformer(cfg),
                                              config=conf)
        return engine

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 64, (8, 16)).astype(np.int32)
    e1 = make(4)
    for _ in range(2):
        loss = e1({"input_ids": ids})
        e1.backward(loss)
        e1.step()
    e1.save_checkpoint(str(tmp_path))
    w1 = jax.device_get(e1.params)

    e2 = make(2)
    e2.load_checkpoint(str(tmp_path))
    jax.tree.map(np.testing.assert_array_equal, w1,
                 jax.device_get(e2.params))
    assert e2.global_steps == 2
    # the values came back AND landed ep-sharded on the NEW topology
    leaves = jax.tree_util.tree_leaves_with_path(e2.params)
    expert = [l for p, l in leaves if "experts" in str(p).lower()]
    assert expert and any("ep" in str(l.sharding.spec) for l in expert), \
        "expert params not resharded over ep after cross-ep load"
    loss = e2({"input_ids": ids})
    e2.backward(loss)
    e2.step()
    assert np.isfinite(float(jax.device_get(loss)))
