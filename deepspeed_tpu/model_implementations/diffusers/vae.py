"""DSVAE — accelerated VAE wrapper for diffusion pipelines.

Reference parity: ``model_implementations/diffusers/vae.py`` (``DSVAE``):
wraps the pipeline's VAE, routing encode/decode through captured CUDA graphs
and the fused spatial kernels (``csrc/spatial``).  TPU version: encode /
decode / forward each become one jitted executable (shape-keyed replay via
CompiledGraphModule); the spatial bias-add fusion is XLA's job and the
``ops.spatial`` helpers are used by converted modules.
"""

from deepspeed_tpu.model_implementations.features.cuda_graph import (
    CompiledGraphModule)


class DSVAE:
    """``DSVAE(module, params)`` where ``module`` is a flax VAE exposing
    ``apply(params, x, method=...)`` with ``encode``/``decode`` methods (or
    plain callables passed via ``encode_fn``/``decode_fn``)."""

    def __init__(self, vae, params=None, enable_cuda_graph=True,
                 encode_fn=None, decode_fn=None):
        self.vae = vae
        self.params = params
        self.config = getattr(vae, "config", None)
        is_flax = hasattr(vae, "apply")
        if encode_fn is None and hasattr(vae, "encode"):
            encode_fn = (lambda p, x: vae.apply(p, x, method=type(vae).encode)) \
                if is_flax else (lambda p, x: vae.encode(x))
        if decode_fn is None and hasattr(vae, "decode"):
            decode_fn = (lambda p, x: vae.apply(p, x, method=type(vae).decode)) \
                if is_flax else (lambda p, x: vae.decode(x))
        fwd_fn = (lambda p, x: vae.apply(p, x)) if hasattr(vae, "apply") \
            else (lambda p, x: vae(x))
        self._encode = CompiledGraphModule(encode_fn, enable_cuda_graph) \
            if encode_fn else None
        self._decode = CompiledGraphModule(decode_fn, enable_cuda_graph) \
            if decode_fn else None
        self._forward = CompiledGraphModule(fwd_fn, enable_cuda_graph)

    def encode(self, x, params=None):
        assert self._encode is not None, "wrapped VAE has no encode method"
        return self._encode(params if params is not None else self.params, x)

    def decode(self, z, params=None):
        assert self._decode is not None, "wrapped VAE has no decode method"
        return self._decode(params if params is not None else self.params, z)

    def __call__(self, x, params=None):
        return self._forward(params if params is not None else self.params, x)
