"""PipelineEngine end-to-end: pipelined transformer trains, matches the
non-pipelined engine's semantics, and composes with ZeRO/bf16."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.transformer import TransformerConfig
from deepspeed_tpu.models.pipeline_transformer import transformer_pipe
from deepspeed_tpu.runtime.pipe.schedule import TrainSchedule, InferenceSchedule


def tiny_cfg(**over):
    base = dict(vocab_size=64, hidden_size=32, num_layers=4, num_heads=4,
                max_seq_len=32, use_flash_attention=False, dtype="float32",
                scan_layers=False, remat=False)
    base.update(over)
    return TransformerConfig(**base)


def pipe_batch(M=2, mb=4, seq=16, vocab=64, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, vocab, (M, mb, seq)).astype(np.int32)}


def make_engine(pp=2, M=2, zero=0, **cfg_over):
    module = transformer_pipe(tiny_cfg(**cfg_over))
    engine, *_ = deepspeed_tpu.initialize(
        model=module,
        config={
            "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": M,
            "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
            "zero_optimization": {"stage": zero},
            "pipeline": {"stages": pp},
        })
    return engine


@pytest.mark.parametrize("pp", [2, 4])
def test_pipeline_transformer_trains(pp):
    engine = make_engine(pp=pp)
    batch = pipe_batch(seed=3)
    losses = [float(jax.device_get(engine.train_batch(batch=batch)))
              for _ in range(6)]
    assert losses[-1] < losses[0], f"pp={pp} no learning: {losses}"


def test_pipeline_with_zero2():
    engine = make_engine(pp=2, zero=2)
    batch = pipe_batch()
    l0 = float(jax.device_get(engine.train_batch(batch=batch)))
    l1 = float(jax.device_get(engine.train_batch(batch=batch)))
    assert np.isfinite(l0) and l1 < l0


def test_pipeline_matches_dense_engine_loss():
    """Pipelined loss at init ≈ dense-engine loss at init for the same
    architecture (different inits → compare magnitude only)."""
    engine = make_engine(pp=2)
    batch = pipe_batch()
    loss = float(jax.device_get(engine.eval_batch(batch=batch)))
    assert abs(loss - np.log(64)) < 0.8   # ~uniform prediction at init


def test_pipeline_forbids_forward_backward():
    engine = make_engine(pp=2)
    with pytest.raises(RuntimeError):
        engine({"input_ids": np.zeros((2, 4), np.int32)})
    with pytest.raises(RuntimeError):
        engine.backward(0.0)
    with pytest.raises(RuntimeError):
        engine.step()


def test_body_param_sharded_over_pp():
    engine = make_engine(pp=4)
    engine.train_batch(batch=pipe_batch())
    body_leaves = jax.tree.leaves(engine.params["body"])
    assert any("pp" in str(l.sharding.spec) for l in body_leaves), \
        "body params not sharded over pp axis"


def test_train_schedule_wavefront():
    sched = TrainSchedule(micro_batches=4, stages=2, stage_id=0)
    steps = sched.steps()
    # first tick on stage 0 loads microbatch 0 and runs forward
    names = [type(c).__name__ for c in steps[0]]
    assert names == ["LoadMicroBatch", "ForwardPass", "SendActivation"]
    # total fwd ticks = M + P - 1
    fwd_ticks = 4 + 2 - 1
    inf = InferenceSchedule(4, 2, 1).steps()
    assert len(inf) == fwd_ticks
    # last stage's first tick is idle (wavefront delay)
    assert inf[0] == []
    assert [type(c).__name__ for c in inf[1]] == ["RecvActivation", "ForwardPass"]


def test_pipeline_opt350m_layout_trains():
    """The OPT-350M layout — post-LN, embed projection, tied embeddings —
    pipelines (round-1 gap: these configs raised NotImplementedError;
    reference ``PipelineModule`` takes arbitrary LayerSpec stacks incl.
    tied embeddings, ``pipe/module.py:85,406-427``)."""
    engine = make_engine(pp=2, pre_layer_norm=False, embed_proj_dim=16,
                         tie_word_embeddings=True)
    batch = pipe_batch(seed=5)
    losses = [float(jax.device_get(engine.train_batch(batch=batch)))
              for _ in range(6)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], f"opt-350m layout no learning: {losses}"
    # tied head: no lm_head params anywhere; embed params carry both roles
    flat = jax.tree_util.tree_flatten_with_path(engine.params)[0]
    names = [jax.tree_util.keystr(p) for p, _ in flat]
    assert not any("lm_head" in n for n in names)


def test_pipeline_moe_trunk_trains():
    """A MoE trunk pipelines with the aux loss threaded through the
    activation pytree (round-1 gap)."""
    engine = make_engine(pp=2, moe_num_experts=4, moe_ep_size=1,
                         moe_every=2)
    batch = pipe_batch(seed=7)
    losses = [float(jax.device_get(engine.train_batch(batch=batch)))
              for _ in range(6)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], f"moe trunk no learning: {losses}"


def test_pipeline_moe_offset_trunk():
    """An in-period MoE offset (first MoE layer < moe_every) pipelines and
    places experts on the offset layers; an offset >= moe_every has an
    aperiodic dense prefix and must fail loudly, not build an all-dense
    trunk."""
    engine = make_engine(pp=2, moe_num_experts=4, moe_ep_size=1,
                         moe_every=2, moe_layer_offset=0)
    batch = pipe_batch(seed=11)
    losses = [float(jax.device_get(engine.train_batch(batch=batch)))
              for _ in range(6)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], f"offset moe trunk no learning: {losses}"
    flat = jax.tree_util.tree_flatten_with_path(engine.params)[0]
    names = [jax.tree_util.keystr(p) for p, _ in flat]
    assert any("moe_mlp" in n for n in names), "experts missing from trunk"

    with pytest.raises(ValueError, match="aperiodic"):
        transformer_pipe(tiny_cfg(moe_num_experts=4, moe_ep_size=1,
                                  moe_every=2, moe_layer_offset=3))


def test_pipeline_postln_matches_dense_loss_at_init():
    """Post-LN pipelined loss at init lands at the uniform-prediction
    magnitude, like the dense model."""
    engine = make_engine(pp=2, pre_layer_norm=False)
    loss = float(jax.device_get(engine.eval_batch(batch=pipe_batch())))
    assert abs(loss - np.log(64)) < 0.8


@pytest.mark.slow
def test_pipeline_memory_bounded_chunks():
    """``pipeline.max_in_flight_microbatches`` gives the reference 1F1B
    schedule's memory property (``schedule.py:189``): peak temp memory is
    FLAT in the microbatch count (only C stage inputs ever stashed), while
    the fill-drain schedule's stash grows linearly with M."""
    def peak_temp(M, C=0):
        module = transformer_pipe(tiny_cfg(hidden_size=128, num_layers=4,
                                           max_seq_len=64))
        engine, *_ = deepspeed_tpu.initialize(
            model=module,
            config={"train_micro_batch_size_per_gpu": 4,
                    "gradient_accumulation_steps": M,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "pipeline": {"stages": 2,
                                 "max_in_flight_microbatches": C}})
        batch = pipe_batch(M=M, seq=64)
        batch = jax.tree.map(jnp.asarray, batch)
        engine._lazy_init_pipe(batch)
        step = engine._get_fused_step()
        lowered = step.lower(engine._params, engine._opt_state,
                             engine._scaler_state,
                             jnp.asarray(1e-3, jnp.float32),
                             jnp.asarray(1, jnp.int32),
                             jax.random.key(0), batch)
        mem = lowered.compile().memory_analysis()
        return int(mem.temp_size_in_bytes)

    slope_unbounded = peak_temp(24) - peak_temp(8)
    slope_bounded = peak_temp(24, C=2) - peak_temp(8, C=2)
    assert slope_unbounded > 0, "fill-drain stash should grow with M"
    # bounded: adding microbatches must cost (nearly) no extra live memory
    assert slope_bounded < 0.1 * slope_unbounded, \
        (slope_bounded, slope_unbounded)


def test_pipeline_chunked_matches_unchunked_loss():
    """Chunked (memory-bounded) and fill-drain schedules compute the same
    global loss and the same training trajectory."""
    def run(C):
        module = transformer_pipe(tiny_cfg())
        engine, *_ = deepspeed_tpu.initialize(
            model=module,
            config={"train_micro_batch_size_per_gpu": 4,
                    "gradient_accumulation_steps": 4,
                    "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
                    "pipeline": {"stages": 2,
                                 "max_in_flight_microbatches": C}})
        batch = pipe_batch(M=4, seed=11)
        return [float(jax.device_get(engine.train_batch(batch=batch)))
                for _ in range(3)]

    plain = run(0)
    chunked = run(2)
    np.testing.assert_allclose(plain, chunked, rtol=2e-4, atol=2e-5)


def test_pipeline_3d_dp_tp_pp_composition():
    """3D parallelism in ONE mesh — dp=2 × tp=2 × pp=2 on the 8-device
    test mesh (reference ``PipeModelDataParallelTopology``,
    ``runtime/pipe/topology.py:244``): trains, loss decreases, and the
    body params carry BOTH the pp and tp axes in their shardings."""
    module = transformer_pipe(tiny_cfg(num_heads=4))
    engine, *_ = deepspeed_tpu.initialize(
        model=module,
        config={
            "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": 2,
            "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
            "pipeline": {"stages": 2},
            "tensor_parallel": {"tp_size": 2},
        })
    assert engine.topology.pp == 2 and engine.topology.tp == 2
    assert engine.topology.edp == 2   # 8 devices / (pp*tp)
    batch = pipe_batch(seed=13)
    losses = [float(jax.device_get(engine.train_batch(batch=batch)))
              for _ in range(4)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], f"3D no learning: {losses}"
    body_specs = [str(l.sharding.spec)
                  for l in jax.tree.leaves(engine.params["body"])]
    assert any("pp" in s for s in body_specs), "body not sharded over pp"
    assert any("tp" in s for s in body_specs), "body not sharded over tp"


def test_pipeline_bad_max_in_flight_raises():
    module = transformer_pipe(tiny_cfg())
    with pytest.raises(ValueError):
        deepspeed_tpu.initialize(
            model=module,
            config={"train_micro_batch_size_per_gpu": 4,
                    "gradient_accumulation_steps": 4,
                    "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
                    "pipeline": {"stages": 2,
                                 "max_in_flight_microbatches": 3}})


def test_pipeline_1f1b_matches_fill_drain_loss():
    """The interleaved 1F1B schedule (hand-rolled per-tick vjp, reference
    ``TrainSchedule`` ``schedule.py:189``) computes the same loss and the
    same training trajectory as the autodiff fill-drain schedule."""
    def run(schedule):
        module = transformer_pipe(tiny_cfg())
        engine, *_ = deepspeed_tpu.initialize(
            model=module,
            config={"train_micro_batch_size_per_gpu": 4,
                    "gradient_accumulation_steps": 4,
                    "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
                    "pipeline": {"stages": 2, "schedule": schedule}})
        batch = pipe_batch(M=4, seed=11)
        return [float(jax.device_get(engine.train_batch(batch=batch)))
                for _ in range(3)]

    plain = run("fill_drain")
    f1b1 = run("1f1b")
    np.testing.assert_allclose(plain, f1b1, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("pp", [2, 4])
def test_pipeline_1f1b_trains(pp):
    module = transformer_pipe(tiny_cfg())
    engine, *_ = deepspeed_tpu.initialize(
        model=module,
        config={"train_micro_batch_size_per_gpu": 4,
                "gradient_accumulation_steps": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
                "pipeline": {"stages": pp, "schedule": "1f1b"}})
    batch = pipe_batch(M=4, seed=3)
    losses = [float(jax.device_get(engine.train_batch(batch=batch)))
              for _ in range(6)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], f"1f1b pp={pp} no learning: {losses}"


def test_pipeline_1f1b_tied_and_postln_layout():
    """OPT-350M-style layout (post-LN, embed projection, tied embeddings)
    under 1F1B: the tied head's gradient flows through BOTH the in-region
    last-stage vjp and the pre-chain cotangent."""
    module = transformer_pipe(tiny_cfg(pre_layer_norm=False,
                                       embed_proj_dim=16,
                                       tie_word_embeddings=True))
    engine, *_ = deepspeed_tpu.initialize(
        model=module,
        config={"train_micro_batch_size_per_gpu": 4,
                "gradient_accumulation_steps": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
                "pipeline": {"stages": 2, "schedule": "1f1b"}})
    batch = pipe_batch(M=4, seed=5)
    losses = [float(jax.device_get(engine.train_batch(batch=batch)))
              for _ in range(6)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], f"1f1b tied layout no learning: {losses}"


def test_pipeline_1f1b_tick_count_and_bubble():
    """Schedule math: the three-phase staging (P-1 fwd-only warmup ticks,
    M combined steady ticks, P-1 bwd-only cooldown ticks) makes the
    wall-clock bubble exactly the reference asynchronous 1F1B's
    (P-1)/(M+P-1) (``runtime/pipe/schedule.py:189``): warmup ticks cost tf
    and cooldown ticks tb, so total = (M+P-1)(tf+tb) — the fill-drain
    equivalent-tick count — at an O(P) stash, strictly beating chunked
    accumulation at the same memory bound."""
    from deepspeed_tpu.parallel.pipeline import (one_f_one_b_phase_ticks,
                                                 one_f_one_b_ticks)
    M, PP, C = 16, 4, 4
    warm, steady, cool = one_f_one_b_phase_ticks(M, PP)
    assert (warm, steady, cool) == (PP - 1, M, PP - 1)
    assert one_f_one_b_ticks(M, PP) == warm + steady + cool == 22
    # wall-clock in (tf+tb) units: warmup/cooldown each cost half a tick
    equivalent_full_ticks = steady + (warm + cool) / 2          # 19
    fill_drain_ticks = M + PP - 1                               # 19 (O(M) stash)
    chunked_ticks = (M // C) * (C + PP - 1)                     # 28
    assert equivalent_full_ticks == fill_drain_ticks
    assert equivalent_full_ticks < chunked_ticks
    bubble = (equivalent_full_ticks - M) / equivalent_full_ticks
    assert abs(bubble - (PP - 1) / (M + PP - 1)) < 1e-12


@pytest.mark.parametrize("schedule", ["fill_drain", "1f1b"])
def test_pipeline_checkpoint_resume_fresh_engine(schedule, tmp_path):
    """A checkpoint saved by a PipelineEngine loads into a FRESH
    PipelineEngine (no prior train step) and training continues: the
    fresh-load path must build the pipe plan (pp-lifted body specs) from
    the loaded shapes and rebuild the pre/body/post module structure on
    the first train_batch without clobbering the restored params."""
    def make(sched):
        module = transformer_pipe(tiny_cfg())
        engine, *_ = deepspeed_tpu.initialize(
            model=module,
            config={"train_micro_batch_size_per_gpu": 4,
                    "gradient_accumulation_steps": 4,
                    "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
                    "pipeline": {"stages": 2, "schedule": sched}})
        return engine

    batch = pipe_batch(M=4, seed=7)
    e = make(schedule)
    for _ in range(3):
        float(jax.device_get(e.train_batch(batch=batch)))
    e.save_checkpoint(str(tmp_path))
    saved_leaf = np.asarray(jax.device_get(jax.tree.leaves(e._params)[0]))

    e2 = make(schedule)
    e2.load_checkpoint(str(tmp_path))
    assert e2.global_steps == e.global_steps
    loaded_leaf = np.asarray(jax.device_get(jax.tree.leaves(e2._params)[0]))
    np.testing.assert_array_equal(saved_leaf, loaded_leaf)
    # the first train_batch rebuilds the module structure — it must NOT
    # clobber the restored params/opt: the resumed step's loss must match
    # the original engine continuing from the same state
    l_resume = float(jax.device_get(e2.train_batch(batch=batch)))
    l_orig = float(jax.device_get(e.train_batch(batch=batch)))
    np.testing.assert_allclose(l_resume, l_orig, rtol=1e-5)


def test_pipeline_1f1b_rejects_chunking():
    module = transformer_pipe(tiny_cfg())
    with pytest.raises(ValueError, match="mutually exclusive"):
        deepspeed_tpu.initialize(
            model=module,
            config={"train_micro_batch_size_per_gpu": 4,
                    "gradient_accumulation_steps": 4,
                    "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
                    "pipeline": {"stages": 2, "schedule": "1f1b",
                                 "max_in_flight_microbatches": 2}})


@pytest.mark.slow
def test_pipeline_1f1b_memory_flat_in_microbatches():
    """1F1B's whole point: the activation stash is the O(P) input ring, so
    peak temp memory is flat in M (the fill-drain stash grows ~linearly)."""
    def peak_temp(M, schedule):
        module = transformer_pipe(tiny_cfg(hidden_size=128, num_layers=4,
                                           max_seq_len=64))
        engine, *_ = deepspeed_tpu.initialize(
            model=module,
            config={"train_micro_batch_size_per_gpu": 4,
                    "gradient_accumulation_steps": M,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "pipeline": {"stages": 2, "schedule": schedule}})
        batch = pipe_batch(M=M, seq=64)
        batch = jax.tree.map(jnp.asarray, batch)
        engine._lazy_init_pipe(batch)
        step = engine._get_fused_step()
        lowered = step.lower(engine._params, engine._opt_state,
                             engine._scaler_state,
                             jnp.asarray(1e-3, jnp.float32),
                             jnp.asarray(1, jnp.int32),
                             jax.random.key(0), batch)
        mem = lowered.compile().memory_analysis()
        return int(mem.temp_size_in_bytes)

    slope_unbounded = peak_temp(24, "fill_drain") - peak_temp(8, "fill_drain")
    slope_1f1b = peak_temp(24, "1f1b") - peak_temp(8, "1f1b")
    assert slope_unbounded > 0, "fill-drain stash should grow with M"
    assert slope_1f1b < 0.1 * slope_unbounded, (slope_1f1b, slope_unbounded)
    # and in absolute terms: growing M only costs ~the raw token ids/labels
    ids_labels_bytes = 16 * 4 * 64 * 4 * 2       # ΔM × mb × seq × int32 × 2
    assert slope_1f1b <= 4 * ids_labels_bytes, slope_1f1b
