"""TPU accelerator implementation.

The TPU analog of the reference's ``accelerator/cuda_accelerator.py`` —
every ABC method mapped onto JAX device APIs instead of torch.cuda.
"""

import os

import jax
import jax.numpy as jnp

from .abstract_accelerator import Accelerator


class TPU_Accelerator(Accelerator):

    def __init__(self, platform="tpu"):
        super().__init__()
        self._name = platform
        self._communication_backend_name = "xla"
        self._seed = 42
        self._key = None
        self._peak_bytes = {}

    # ----------------------------------------------------------------- #
    def device_name(self, device_index=None):
        if device_index is None:
            return self._name
        return f"{self._name}:{device_index}"

    def is_available(self):
        try:
            return len(self.devices()) > 0
        except RuntimeError:
            return False

    def devices(self):
        try:
            return jax.local_devices()
        except RuntimeError:
            return []

    def device_count(self):
        return jax.local_device_count()

    def global_device_count(self):
        return jax.device_count()

    def current_device(self):
        return self.devices()[0]

    def current_device_name(self):
        return self.device_name(0)

    # ----------------------------------------------------------------- #
    def synchronize(self, device_index=None):
        # XLA dispatch is async; a tiny reduction forced to completion acts
        # as a full device barrier for profiling/timers.
        jnp.zeros(()).block_until_ready()

    # ----------------------------------------------------------------- #
    def manual_seed(self, seed):
        self._seed = int(seed)
        self._key = jax.random.key(self._seed)

    def initial_seed(self):
        return self._seed

    def rng_key(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)
        self._key, sub = jax.random.split(self._key)
        return sub

    # ----------------------------------------------------------------- #
    def memory_stats(self, device_index=None):
        dev = self.devices()[device_index or 0]
        try:
            stats = dev.memory_stats() or {}
        except Exception:
            stats = {}
        in_use = stats.get("bytes_in_use", 0)
        peak = self._peak_bytes.get(dev.id, 0)
        if in_use > peak:
            self._peak_bytes[dev.id] = peak = in_use
        stats.setdefault("peak_bytes_in_use", peak)
        return stats

    def memory_allocated(self, device_index=None):
        return self.memory_stats(device_index).get("bytes_in_use", 0)

    def max_memory_allocated(self, device_index=None):
        s = self.memory_stats(device_index)
        return max(s.get("peak_bytes_in_use", 0), s.get("bytes_in_use", 0))

    def reset_peak_memory_stats(self, device_index=None):
        dev = self.devices()[device_index or 0]
        self._peak_bytes[dev.id] = 0

    def total_memory(self, device_index=None):
        return self.memory_stats(device_index).get("bytes_limit", 0)

    def available_memory(self, device_index=None):
        s = self.memory_stats(device_index)
        return s.get("bytes_limit", 0) - s.get("bytes_in_use", 0)

    # ----------------------------------------------------------------- #
    def is_bf16_supported(self):
        return True

    def is_fp16_supported(self):
        return True

    def supported_dtypes(self):
        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8]

    # ----------------------------------------------------------------- #
    def communication_backend_name(self):
        return self._communication_backend_name

    def get_op_builder(self, class_name):
        from deepspeed_tpu.ops.op_builder import get_builder
        return get_builder(class_name)

    def on_accelerator(self, array):
        try:
            shards = getattr(array, "sharding", None)
            if shards is None:
                return False
            platforms = {d.platform for d in shards.device_set}
            return platforms <= {self._name, "axon"}
        except Exception:
            return False


class CPU_Accelerator(TPU_Accelerator):
    """CPU-simulated accelerator for hostless CI (the analog of the
    reference's fake-backend test path, ``tests/unit/common.py:92``) —
    identical surface, ``platform == "cpu"``."""

    def __init__(self):
        super().__init__(platform="cpu")

    def is_bf16_supported(self):
        return True

    def total_memory(self, device_index=None):
        try:
            import psutil
            return psutil.virtual_memory().total
        except Exception:
            return int(os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES"))

    def available_memory(self, device_index=None):
        try:
            return int(os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_AVPHYS_PAGES"))
        except Exception:
            return 0
