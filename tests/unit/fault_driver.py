"""Subprocess driver for the kill-and-resume proof
(``test_fault_tolerance.py``).

Trains SimpleModel under ``run_resilient`` with data derived from
``engine.global_steps`` (the determinism contract), appending
``step,repr(loss)`` lines to ``--losses`` after every completed step.  The
test harness arms ``DSTPU_FAULT_INJECT`` (e.g.
``point=ckpt.before_latest_swap,action=exit,at=2``) so this process dies
mid-save with ``os._exit`` — no cleanup, the honest SIGKILL simulation —
then relaunches it clean and compares the merged loss trajectory bitwise
against an uninterrupted run.

Exit codes: 0 done, 3 preempted, 4 failed (and the injected ``exit_code``
— default 17 — when a kill fires).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = \
        flags + " --xla_force_host_platform_device_count=8"
os.environ.setdefault("DSTPU_ACCELERATOR", "cpu")
sys.path.insert(0, os.environ["DSTPU_REPO_ROOT"])
sys.path.insert(0, os.path.join(os.environ["DSTPU_REPO_ROOT"], "tests",
                                "unit"))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Per-harness compile cache so relaunches skip XLA compilation.  NEVER
# point this at the suite's tests/.jax_compile_cache: this process is
# killed with os._exit at arbitrary seams, and a truncated cache write
# makes every LATER process that loads the entry abort natively deep in
# XLA (observed: deterministic SIGABRT in engine.step until the poisoned
# entry was pruned).  Isolation bounds the blast radius to this test's
# own tmp dir.
_cache = os.environ.get("DSTPU_DRIVER_CACHE")
if _cache:
    jax.config.update("jax_compilation_cache_dir", _cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.runtime.fault.supervisor import run_resilient  # noqa: E402
from simple_model import SimpleModel, random_batch  # noqa: E402


def main():
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument("--ckpt-dir", required=True)
    parser.add_argument("--max-steps", type=int, default=6)
    parser.add_argument("--save-interval", type=int, default=2)
    parser.add_argument("--losses", required=True)
    args = parser.parse_args()

    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2},
        "seed": 7,
        "fault": {"enabled": True, "checksum": "crc32",
                  "backoff_base_secs": 0.01, "backoff_max_secs": 0.05},
    }
    engine, *_ = deepspeed_tpu.initialize(model=SimpleModel(hidden_dim=16),
                                          config=config)

    def step_fn(engine):
        # data is a pure function of the resumable step counter — the
        # resumed trajectory replays exactly the batches the uninterrupted
        # run would have seen
        batch = random_batch(batch_size=16, seed=engine.global_steps)
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        with open(args.losses, "a") as f:
            f.write(f"{engine.global_steps},"
                    f"{float(jax.device_get(loss))!r}\n")

    status, info = run_resilient(engine, step_fn,
                                 checkpoint_dir=args.ckpt_dir,
                                 max_steps=args.max_steps,
                                 save_interval=args.save_interval)
    print(f"[driver] {status} {info}", flush=True)
    return {"done": 0, "preempted": 3, "failed": 4}[status]


if __name__ == "__main__":
    sys.exit(main())
