"""Step-1 SFT — the framework's headline workload (DeepSpeed-Chat step 1,
reference ``BASELINE.json``): supervised fine-tuning of an OPT-family model
with ZeRO sharding, bf16, and the fused train step.

Run on one chip:        python examples/train_sft.py
Run on a CPU dev mesh:  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
                        JAX_PLATFORMS=cpu DSTPU_ACCELERATOR=cpu \
                        python examples/train_sft.py --model tiny
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

# a sitecustomize may pin a hardware platform before this script runs; the
# live jax config must be updated before first device use (env is too late)
if os.environ.get("DSTPU_ACCELERATOR") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="opt-125m")
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--micro_bs", type=int, default=4)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--zero", type=int, default=3)
    ap.add_argument("--ckpt_dir", default=None)
    args = ap.parse_args()

    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.opt import opt_config
    from deepspeed_tpu.models.transformer import Transformer, TransformerConfig

    if args.model == "tiny":
        cfg = TransformerConfig(vocab_size=512, hidden_size=64, num_layers=2,
                                num_heads=4, max_seq_len=args.seq,
                                dtype="float32", use_flash_attention=False)
    else:
        cfg = opt_config(args.model, max_seq_len=args.seq, dtype="bfloat16")

    engine, optimizer, _, scheduler = deepspeed_tpu.initialize(
        model=Transformer(cfg),
        config={
            "train_micro_batch_size_per_gpu": args.micro_bs,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 9.65e-6, "weight_decay": 0.0}},
            "scheduler": {"type": "WarmupDecayLR",
                          "params": {"warmup_num_steps": 10,
                                     "total_num_steps": args.steps}},
            "bf16": {"enabled": args.model != "tiny"},
            "zero_optimization": {"stage": args.zero},
            "gradient_clipping": 1.0,
        })

    # stand-in for a tokenized SFT dataset: {"input_ids": [B, S]}
    rng = np.random.default_rng(0)
    for step in range(args.steps):
        batch = {"input_ids": rng.integers(
            0, cfg.vocab_size, (args.micro_bs * max(engine.topology.dp, 1),
                                args.seq)).astype(np.int32)}
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        if step % 5 == 0:
            print(f"step {step}: loss {float(jax.device_get(loss)):.4f}")

    if args.ckpt_dir:
        engine.save_checkpoint(args.ckpt_dir)
        print("checkpoint saved to", args.ckpt_dir)


if __name__ == "__main__":
    main()
