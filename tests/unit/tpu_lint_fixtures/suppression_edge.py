"""Suppression edge cases: function-level disables on DECORATED functions
(comment on the decorator line, on the last of several decorators, and on
the def line below decorators) and multi-rule disables on one line."""
import functools

import jax
from deepspeed_tpu.tools.lint.hotpath import hot_path


@hot_path("fixture.deco1")  # tpu-lint: disable=TL001 -- suppression on the decorator line covers the body
def on_decorator_line(loss):
    return loss.item()


@functools.partial(jax.jit, donate_argnums=())
@hot_path("fixture.deco2")  # tpu-lint: disable=TL001 -- suppression on the LAST of stacked decorators
def on_last_decorator(loss):
    return loss.item()


@hot_path("fixture.deco3")
def on_def_line_below_decorator(loss):  # tpu-lint: disable=TL001 -- suppression on the def line under a decorator
    return loss.item()


@hot_path("fixture.multi")
def multi_rule_one_line(loss, config):
    # one comment, two rules: both must be suppressed on this line
    return loss.item(), config["lr"]  # tpu-lint: disable=TL001,TL005 -- epoch-boundary drain reads both


@hot_path("fixture.multi2")
def multi_rule_leak(loss, config):
    # TL001 suppressed, TL005 must still fire on this line
    return loss.item(), config["lr"]  # tpu-lint: disable=TL001 -- only the host read is intentional
