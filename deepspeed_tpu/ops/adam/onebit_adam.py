"""1-bit Adam — TPU-native re-design of reference
``runtime/fp16/onebit/adam.py:13`` (OnebitAdam) + the compressed-allreduce
backends (``runtime/comm/nccl.py:54``).

Algorithm (Tang et al., "1-bit Adam"): run exact Adam for ``freeze_step``
warmup steps; afterwards freeze the variance term and communicate only the
*sign* of the momentum with an error-feedback buffer.  On TPU, gradients are
already reduced by GSPMD before the optimizer sees them (over ICI compression
buys nothing), so the compression stage models the DCN analog: the momentum
update is quantized to sign×mean-magnitude with error feedback — numerically
the same update rule the reference applies after its compressed allreduce.

``ZeroOneAdam`` (reference ``onebit/zoadam.py:13``) differs only in its
variance/lr-freeze schedule and maps onto the same machinery.
"""

from typing import NamedTuple, Any

import jax
import jax.numpy as jnp


class OnebitAdamState(NamedTuple):
    exp_avg: Any
    exp_avg_sq: Any
    error_feedback: Any


def sign_compress(corrected):
    """Sign-compress a pytree against ONE flat-buffer scale, ``‖buf‖₂/√n``
    (reference ``nccl.py:54`` compressed_allreduce normalizes its flat worker
    chunk the same way).  A per-leaf ``mean|·|`` scale hands small-variance
    coordinates outsize ``m/√v`` steps that the error-feedback loop then
    amplifies — at short freeze_steps that diverges within a few updates.
    Returns ``(compressed_tree, scale)``."""
    leaves = jax.tree.leaves(corrected)
    sumsq = sum(jnp.sum(jnp.square(l)) for l in leaves)
    n = sum(l.size for l in leaves)
    scale = jnp.sqrt(sumsq / n)
    return jax.tree.map(lambda c: jnp.sign(c) * scale, corrected), scale


class OnebitAdam:

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                 freeze_step=100000, cuda_aware=False, comm_backend_name="xla",
                 master_dtype=jnp.float32):
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.freeze_step = freeze_step
        self.master_dtype = master_dtype

    def init(self, params):
        zeros = lambda p: jnp.zeros(p.shape, dtype=self.master_dtype)
        return OnebitAdamState(exp_avg=jax.tree.map(zeros, params),
                               exp_avg_sq=jax.tree.map(zeros, params),
                               error_feedback=jax.tree.map(zeros, params))

    def update(self, grads, state, params, lr=None, step=1):
        lr = self.lr if lr is None else lr
        b1, b2, eps, wd = self.beta1, self.beta2, self.eps, self.weight_decay
        step = jnp.asarray(step, dtype=jnp.float32)
        warmup = step <= self.freeze_step
        bc1 = 1.0 - b1 ** step
        bc2 = 1.0 - b2 ** jnp.minimum(step, float(self.freeze_step))

        md = self.master_dtype
        m_new = jax.tree.map(lambda m, g: b1 * m + (1.0 - b1) * g.astype(md),
                             state.exp_avg, grads)
        # compression stage (post-warmup): flat-buffer sign compression with
        # error feedback
        corrected = jax.tree.map(jnp.add, m_new, state.error_feedback)
        compressed, _ = sign_compress(corrected)

        def leaf(p, g, m_n, c, q, v, e):
            g32 = g.astype(md)
            p32 = p.astype(md)
            e_new = jnp.where(warmup, e, c - q)
            m_eff = jnp.where(warmup, m_n, q)
            # variance frozen after warmup (reference adam.py freeze)
            v_new = jnp.where(warmup, b2 * v + (1.0 - b2) * (g32 * g32), v)
            upd = (m_eff / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            if wd != 0.0:
                upd = upd + wd * p32
            return (p32 - lr * upd).astype(p.dtype), m_eff, v_new, e_new

        out = jax.tree.map(leaf, params, grads, m_new, corrected, compressed,
                           state.exp_avg_sq, state.error_feedback)
        is_t = lambda t: isinstance(t, tuple)
        pick = lambda i: jax.tree.map(lambda t: t[i], out, is_leaf=is_t)
        return pick(0), OnebitAdamState(pick(1), pick(2), pick(3))


class ZeroOneAdam(OnebitAdam):
    """0/1 Adam (reference ``onebit/zoadam.py:13``).

    Unlike 1-bit Adam's hard warmup/freeze split, 0/1 Adam compresses from
    step one and *adaptively thins* state refreshes:

    * the variance is refreshed only at geometrically spaced refresh steps:
      ``var_update_scaler`` refreshes at interval 1, then ``var_update_scaler``
      at interval 2, then 4, ... (interval doubling per *refresh segment*,
      capped at 2^``local_step_clipper``), until ``var_freeze_step`` freezes
      it for good — the reference's variance-update policy;
    * between refreshes the update reuses the stale variance — the "0" steps;
      refresh steps are the "1" steps.  ``local_step_scaler`` is accepted for
      config parity (the reference's lr-freeze/local-step machinery is a
      communication-skipping device that GSPMD makes moot).
    """

    def __init__(self, var_freeze_step=100000, var_update_scaler=16,
                 local_step_scaler=32678, local_step_clipper=16, **kw):
        kw.pop("freeze_step", None)
        super().__init__(freeze_step=var_freeze_step, **kw)
        self.var_update_scaler = var_update_scaler
        self.local_step_scaler = local_step_scaler
        self.local_step_clipper = local_step_clipper

    def _is_refresh_step(self, step):
        """True at geometrically spaced refresh steps.  Segment j holds
        ``R = var_update_scaler`` refreshes at interval 2^j and starts after
        step ``S_j = R·(2^j − 1)``; a step refreshes iff its offset into its
        segment is a multiple of the segment interval."""
        R = float(self.var_update_scaler)
        j = jnp.floor(jnp.log2(jnp.maximum(step / R + 1.0, 1.0)))
        j = jnp.minimum(j, float(self.local_step_clipper))
        interval = 2.0 ** j
        seg_start = R * (interval - 1.0)
        return jnp.mod(step - seg_start, interval) < 0.5

    def update(self, grads, state, params, lr=None, step=1):
        lr = self.lr if lr is None else lr
        b1, b2, eps, wd = self.beta1, self.beta2, self.eps, self.weight_decay
        step = jnp.asarray(step, dtype=jnp.float32)
        refresh = self._is_refresh_step(step) & (step <= self.freeze_step)
        bc1 = 1.0 - b1 ** step
        bc2 = 1.0 - b2 ** jnp.minimum(step, float(self.freeze_step))

        md = self.master_dtype
        m_new = jax.tree.map(lambda m, g: b1 * m + (1.0 - b1) * g.astype(md),
                             state.exp_avg, grads)
        # compression is always on in 0/1 Adam
        corrected = jax.tree.map(jnp.add, m_new, state.error_feedback)
        compressed, _ = sign_compress(corrected)

        def leaf(p, g, c, q, v):
            g32 = g.astype(md)
            p32 = p.astype(md)
            e_new = c - q
            v_new = jnp.where(refresh, b2 * v + (1.0 - b2) * (g32 * g32), v)
            upd = (q / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            if wd != 0.0:
                upd = upd + wd * p32
            return (p32 - lr * upd).astype(p.dtype), q, v_new, e_new

        out = jax.tree.map(leaf, params, grads, corrected, compressed,
                           state.exp_avg_sq)
        is_t = lambda t: isinstance(t, tuple)
        pick = lambda i: jax.tree.map(lambda t: t[i], out, is_leaf=is_t)
        return pick(0), OnebitAdamState(pick(1), pick(2), pick(3))
