"""Continuous-batching serving (``docs/serving.md``): slot-based in-flight
batching over the inference engine — a request queue, fixed-shape KV slot
lanes, admission prefill through the donated per-chunk executable, and ONE
reusable decode-step program that advances every live slot per iteration
(slot occupancy rides traced arguments, so admissions and EOS retirements
never recompile anything).

``ServingEngine`` is imported lazily: ``inference/config.py`` embeds
:class:`ServingConfig`, and an eager import here would cycle back through
``inference/engine.py``.
"""

from deepspeed_tpu.inference.serving.config import ServingConfig
from deepspeed_tpu.inference.serving.paging import (PagePool,
                                                    PrefixIndex)
from deepspeed_tpu.inference.serving.slo import (CircuitBreaker,
                                                 CircuitOpen, DrainTimeout,
                                                 QueueFull, RequestResult,
                                                 RequestStatus)

__all__ = ["ServingConfig", "ServingEngine", "ServeRequest",
           "RequestStatus", "RequestResult", "QueueFull", "CircuitOpen",
           "DrainTimeout", "CircuitBreaker", "TokenStream",
           "serve_resilient", "ServingHTTPFrontend", "serve_http",
           "FairnessTracker", "PagePool", "PrefixIndex"]


def __getattr__(name):
    if name in ("ServingEngine", "ServeRequest"):
        from deepspeed_tpu.inference.serving import engine as _engine
        return getattr(_engine, name)
    if name == "serve_resilient":
        from deepspeed_tpu.inference.serving.resilient import \
            serve_resilient
        return serve_resilient
    if name == "TokenStream":
        from deepspeed_tpu.inference.serving.slo import TokenStream
        return TokenStream
    if name in ("ServingHTTPFrontend", "serve_http", "FairnessTracker"):
        from deepspeed_tpu.inference.serving import frontend
        return getattr(frontend, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
