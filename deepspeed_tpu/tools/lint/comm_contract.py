"""Communication-cost contracts: byte-level comm budgets + the mesh-scaling
prover (``ds_lint --comm``).

PR 7's contract layer locks collective *counts and schedules*; this module
extends it to *bytes moved*.  For every optimized-HLO program it parses the
collective instructions and computes per-collective byte volumes::

    bytes(instance) = sum(operand shape x dtype width)
                      x replica-group size x number of groups

i.e. the total wire volume the instruction moves across the mesh per step
(``collective-permute`` uses its ``source_target_pairs`` count instead of a
group product).  This is a locked COST MODEL, not a cable measurement — its
value is that it is deterministic, diffable, and monotone in the two things
that regress: shard size and group span.  An accidentally replicated
activation shows up as "all-gather bytes: 2.1MB -> 67MB" in a lockfile
diff, which is reviewable; a bare count bump is not.

The **mesh-scaling prover** compiles every ``parallel/plans.py`` plan at
each mesh point in ``plans.MESH_POINTS`` ({1, 2, 4, 8}) and builds a
bytes-per-chip scaling table.  A collective whose per-chip volume GROWS
with mesh size is the classic replicated-tensor smell (a well-sharded
tensor's per-chip traffic stays flat or falls as chips are added); every
growing op must be declared in the plan's ``allowed_growth`` with a
reviewable reason, or the prover fails.  The locked table is the dry-run
scaling evidence ROADMAP item 1 gates its real-chip bench phase on.

Contracts are defined under the tier-1 harness (CPU, 8 virtual devices);
the CLI forces the same environment as ``--contracts``.
"""

import json
import os
import re

# ------------------------------------------------------------------ #
# Optimized-HLO parsing
# ------------------------------------------------------------------ #
_DTYPE_BYTES = {
    "pred": 1, "s2": 1, "s4": 1, "s8": 1, "u2": 1, "u4": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
    # fp8 families print as e.g. f8e4m3fn — all one byte wide
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2fnuz": 1,
}

# dtype tokens carry a digit (f32, bf16, s8) except boolean 'pred'
_SHAPE_RE = re.compile(r"\b(pred|[a-z]+[0-9]+[a-z0-9]*)\[([0-9,]*)\]")
_OP_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute)(?:-start)?\(")
_GROUPS_EXPLICIT_RE = re.compile(
    r"replica_groups=\{(\{[0-9, ]*\}(?:,\s*\{[0-9, ]*\})*)\}")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=\[")
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{\d+,\s*\d+\},?\s*)+)\}")

# StableHLO mnemonics in an un-optimized lowering — a cheap "does this
# program communicate at all?" probe that costs no compile
_STABLEHLO_COLLECTIVES = ("stablehlo.all_reduce", "stablehlo.all_gather",
                          "stablehlo.all_to_all", "stablehlo.reduce_scatter",
                          "stablehlo.collective_permute",
                          "stablehlo.collective_broadcast")


def shape_bytes(dtype, dims):
    """Byte size of one typed HLO shape, e.g. ('bf16', '2,64') -> 256."""
    n = 1
    for d in dims.split(","):
        if d.strip():
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def parse_hlo_comm(hlo_text, world):
    """``{op: {count, bytes_per_step}}`` from optimized HLO text.

    Handles explicit (``{{0,1},{2,3}}``) and iota (``[4,2]<=[8]``) replica
    groups, tuple-shaped variadic operands, async ``-start`` forms (the
    ``-done`` halves are skipped so nothing double-counts), and
    ``collective-permute``'s pair list.  An instruction with no
    ``replica_groups`` spans the whole ``world``."""
    out = {}
    for line in hlo_text.splitlines():
        m = _OP_RE.search(line)
        if m is None or "-done(" in line:
            continue
        op = m.group(1)
        # balanced-paren scan for the operand span (operand shapes are
        # typed in HLO text; metadata braces never enter this span)
        start = m.end()
        depth, i = 1, start
        while i < len(line) and depth:
            if line[i] == "(":
                depth += 1
            elif line[i] == ")":
                depth -= 1
            i += 1
        operands, tail = line[start:i - 1], line[i:]
        op_bytes = sum(shape_bytes(d, s)
                       for d, s in _SHAPE_RE.findall(operands))
        gi = _GROUPS_IOTA_RE.search(tail)
        ge = _GROUPS_EXPLICIT_RE.search(tail)
        if gi:
            n_groups, group = int(gi.group(1)), int(gi.group(2))
        elif ge:
            groups = re.findall(r"\{([0-9, ]*)\}", ge.group(1))
            n_groups = len(groups)
            group = len([x for x in groups[0].split(",") if x.strip()]) \
                if groups else world
        else:
            n_groups, group = 1, world
        pairs = _PAIRS_RE.search(tail)
        if op == "collective-permute" and pairs:
            total = op_bytes * pairs.group(1).count("{")
        else:
            total = op_bytes * group * n_groups
        entry = out.setdefault(op, {"count": 0, "bytes_per_step": 0})
        entry["count"] += 1
        entry["bytes_per_step"] += total
    return out


def lowered_has_collectives(stablehlo_text):
    """True when an UN-optimized lowering could communicate: it mentions
    an explicit collective (shard_map programs), or a non-replicated
    device assignment (``devices=[...]`` inside a sharding annotation —
    GSPMD inserts the collectives for those only at COMPILE time, so the
    mnemonic probe alone would miss a mesh-sharded jit and lock it an
    empty budget).  The single-chip hot-path programs answer False on
    both, which makes their comm budget ``{}`` without paying for a
    compile; replicated-only sharding annotations don't trip the probe."""
    return any(op in stablehlo_text for op in _STABLEHLO_COLLECTIVES) \
        or "devices=[" in stablehlo_text


def fmt_bytes(n):
    """Human-readable bytes for diffs: 2155872 -> '2.1MB'."""
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}GB"


# ------------------------------------------------------------------ #
# Mesh-scaling table + growth analysis
# ------------------------------------------------------------------ #
# per-chip growth below this ratio between consecutive mesh points is
# treated as schedule noise (padding, fusion boundaries), not replication
GROWTH_TOLERANCE = 1.02


def scaling_entry(world, mesh, comm):
    """One scaling-table row: per-op totals and bytes-per-chip at one
    mesh size."""
    per_chip = {op: v["bytes_per_step"] // world
                for op, v in sorted(comm.items())}
    return {
        "world": int(world),
        "mesh": {k: int(v) for k, v in sorted(dict(mesh).items())},
        "collectives": {op: dict(v) for op, v in sorted(comm.items())},
        "bytes_per_chip": per_chip,
        "bytes_per_chip_total": sum(per_chip.values()),
    }


def growth_flags(table):
    """Ops whose per-chip volume grows between consecutive mesh points.

    ``table`` is a list of scaling entries ordered by world.  Returns
    ``{op: ["2->4: 12.3KB -> 45.6KB/chip", ...]}`` — per-chip bytes
    increasing by more than ``GROWTH_TOLERANCE`` anywhere in the
    trajectory flags the op (the replicated-tensor smell: well-sharded
    traffic stays flat or falls per chip as chips are added).  An op
    APPEARING at a larger mesh (absent at the previous multi-chip point)
    is flagged too — new-axis traffic is exactly how a replicated tensor
    sneaks in undeclared; only the 1->2 transition is exempt, since a
    one-chip mesh has no collectives for anything to be "absent" from."""
    flags = {}
    for prev, nxt in zip(table, table[1:]):
        for op, b in nxt["bytes_per_chip"].items():
            was = prev["bytes_per_chip"].get(op)
            if was and b > was * GROWTH_TOLERANCE:
                flags.setdefault(op, []).append(
                    f"{prev['world']}->{nxt['world']}: "
                    f"{fmt_bytes(was)} -> {fmt_bytes(b)}/chip")
            elif not was and b and prev["world"] > 1:
                flags.setdefault(op, []).append(
                    f"appears at mesh {nxt['world']}: "
                    f"{fmt_bytes(b)}/chip")
    return flags


def build_scaling_contract(plan_builder, mesh_points=None, progress=None,
                           reuse_rows=None):
    """Compile one plan family at every mesh point and return its locked
    scaling contract: the per-world table, the growth-flag set, and the
    plan's declared ``allowed_growth`` reasons.

    ``reuse_rows`` optionally maps ``world -> scaling row`` for points
    already compiled elsewhere (the contract gate derives the canonical
    world=8 row from the locked-schedule compile, so the table's top row
    IS the locked schedule's program and is never compiled twice)."""
    import sys
    from deepspeed_tpu.parallel import plans
    from deepspeed_tpu.parallel.topology import reset_topology
    if mesh_points is None:
        owner = sys.modules.get(plan_builder.__module__)
        mesh_points = getattr(owner, "MESH_POINTS", plans.MESH_POINTS)
    table, name, allowed = [], None, {}
    for world in sorted(mesh_points):
        row = (reuse_rows or {}).get(world)
        if row is None:
            if progress:
                progress(f"compiling {plan_builder.__name__} @ mesh "
                         f"{world}")
            reset_topology()
            try:
                plan = plan_builder(world)
                text = plan.fn.lower(*plan.args).compile().as_text() or ""
                comm = parse_hlo_comm(text, world)
            finally:
                reset_topology()
            name = name or plan.name
            if plan.allowed_growth:
                allowed = dict(plan.allowed_growth)
            row = scaling_entry(world, plan.mesh, comm)
        table.append(row)
    flags = growth_flags(table)
    return name, {
        "kind": "mesh_scaling",
        "points": table,
        "grows_with_mesh": {op: trans
                            for op, trans in sorted(flags.items())},
        "allowed_growth": dict(sorted(allowed.items())),
    }


def validate_scaling_contract(name, contract):
    """Semantic invariants of a scaling contract, checked on top of the
    exact locked table: every growing collective must carry a declared
    reason, and a mesh of one chip must move zero bytes."""
    problems = []
    allowed = contract.get("allowed_growth", {})
    for op, transitions in contract.get("grows_with_mesh", {}).items():
        if op not in allowed:
            problems.append(
                f"per-chip {op} volume GROWS with mesh size "
                f"({'; '.join(transitions)}) — the replicated-tensor "
                f"smell; shard the tensor or declare the growth in the "
                f"plan's allowed_growth with a reason")
    for row in contract.get("points", []):
        if row["world"] == 1 and row["bytes_per_chip_total"]:
            problems.append(
                f"mesh of 1 chip schedules collective traffic "
                f"({fmt_bytes(row['bytes_per_chip_total'])}/chip) — "
                f"phantom communication")
    return [f"{name}: {p}" for p in problems]


def diff_scaling(name, locked, fresh):
    """Readable diff of one plan's scaling contract (empty = match)."""
    out = []
    lp = {r["world"]: r for r in locked.get("points", [])}
    fp = {r["world"]: r for r in fresh.get("points", [])}
    for world in sorted(set(lp) | set(fp)):
        lo, fr = lp.get(world), fp.get(world)
        if lo is None or fr is None:
            out.append(f"  mesh {world}: "
                       f"{'added' if lo is None else 'removed'} point")
            continue
        ops = sorted(set(lo["bytes_per_chip"]) | set(fr["bytes_per_chip"]))
        for op in ops:
            a = lo["bytes_per_chip"].get(op, 0)
            b = fr["bytes_per_chip"].get(op, 0)
            if a != b:
                out.append(f"  mesh {world} {op}: {fmt_bytes(a)} -> "
                           f"{fmt_bytes(b)} per chip")
        # the locked per-point schedule entries too: an instance-count or
        # sub-world-byte drift (integer bytes-per-chip truncation) must
        # not slide through a clean-looking per-chip table
        lc, fc = lo.get("collectives", {}), fr.get("collectives", {})
        for op in sorted(set(lc) | set(fc)):
            a, b = lc.get(op), fc.get(op)
            if a != b:
                out.append(
                    f"  mesh {world} {op} schedule: "
                    f"{a and a['count']}x/{fmt_bytes((a or {}).get('bytes_per_step', 0))}"
                    f" -> {b and b['count']}x/"
                    f"{fmt_bytes((b or {}).get('bytes_per_step', 0))}")
        if lo["mesh"] != fr["mesh"]:
            out.append(f"  mesh {world} axes: {lo['mesh']} -> {fr['mesh']}")
    for field in ("grows_with_mesh", "allowed_growth"):
        lo, fr = locked.get(field, {}), fresh.get(field, {})
        for op in sorted(set(lo) | set(fr)):
            if lo.get(op) != fr.get(op):
                out.append(f"  {field}[{op}]: {lo.get(op)!r} -> "
                           f"{fr.get(op)!r}")
    return [f"{name}:"] + out if out else []


# ------------------------------------------------------------------ #
# CLI (``ds_lint --comm``): sweep + extraction + scaling prover
# ------------------------------------------------------------------ #
def _plans_module():
    """The plans module under analysis — overridable for the synthetic-
    break tests (a fixture module with a deliberately replicated plan).
    The override is a dotted module name or a ``.py`` path."""
    import importlib
    override = os.environ.get("DSTPU_COMM_PLANS_MODULE")
    if override and override.endswith(".py"):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "dstpu_comm_fixture_plans", override)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    if override:
        return importlib.import_module(override)
    from deepspeed_tpu.parallel import plans
    return plans


def check_scaling_against_lockfile(progress=None, plans_mod=None):
    """(ok, lines).  Rebuild every plan's scaling contract, validate the
    growth invariants, and diff against the ``mesh_scaling`` section of
    ``PROGRAMS.lock`` (when the plans module is overridden, validation
    still runs but the lockfile diff is skipped — fixture plans are not
    locked)."""
    from deepspeed_tpu.tools.lint import contract as contract_mod
    overridden = plans_mod is not None or \
        bool(os.environ.get("DSTPU_COMM_PLANS_MODULE"))
    plans_mod = plans_mod or _plans_module()
    lines, ok = [], True
    locked = {}
    if not overridden:
        try:
            locked = contract_mod.load_lockfile().get("mesh_scaling", {})
        except FileNotFoundError:
            # nothing to diff against: fail fast instead of paying the
            # full compile sweep for an answer known at the first line
            return False, [
                f"{contract_mod.LOCKFILE_NAME} missing — generate with "
                f"ds_lint --contracts --update"]
    mesh_points = getattr(plans_mod, "MESH_POINTS", None)
    for builder in plans_mod.PLAN_BUILDERS:
        name, fresh = build_scaling_contract(builder, mesh_points,
                                             progress=progress)
        problems = validate_scaling_contract(name, fresh)
        if problems:
            ok = False
            lines.extend(problems)
        if overridden:
            continue
        if name not in locked:
            ok = False
            lines.append(f"{name}: no mesh_scaling contract in "
                         f"{contract_mod.LOCKFILE_NAME} — run "
                         f"ds_lint --contracts --update")
            continue
        diff = diff_scaling(name, locked[name], fresh)
        if diff:
            ok = False
            lines.extend(diff)
    return ok, lines


def main(paths=None):
    """The ``--comm`` gate: TL010/TL011 sweep over ``paths`` (default: the
    installed package), then the mesh-scaling prover.  Exit 1 on any
    unsuppressed finding, growth violation, or lockfile drift."""
    from deepspeed_tpu.tools.lint.core import run_lint
    if not paths:
        import deepspeed_tpu
        paths = [os.path.dirname(os.path.abspath(deepspeed_tpu.__file__))]
    findings, stats = run_lint(paths, rules={"TL010", "TL011"})
    for f in findings:
        print(f)
    suppressed = sum(stats["suppressed"].values())
    print(f"tpu-lint[comm]: {len(findings)} finding(s), {suppressed} "
          f"suppressed, {stats['files']} file(s) checked")
    if findings:
        return 1                      # static break: skip the slow prover
    progress = lambda msg: print(f"[comm] {msg}", flush=True)
    ok, lines = check_scaling_against_lockfile(progress=progress)
    if ok:
        print("[comm] OK — every plan's mesh-scaling contract holds "
              "(per-chip volumes locked, no undeclared growth)")
        return 0
    print("[comm] COMM-CONTRACT BREAK:")
    for line in lines:
        print(f"  {line}")
    print("[comm] intentional? regenerate with ds_lint --contracts "
          "--update and review the bytes diff like any lockfile bump")
    return 1


if __name__ == "__main__":
    import sys
    from deepspeed_tpu.tools.lint import contract as _c
    _c.ensure_harness_env()
    sys.exit(main(sys.argv[1:] or None))
