"""Persistent compilation & executable cache — compile once per machine.

Compilation is this framework's dominant cold-path cost: every train step,
prefill chunk and unrolled decode program is a multi-minute XLA compile at
OPT-1.3B+ scale (the round-5 bench lost its whole record to ONE ~40-min
cold compile).  This module makes compilation a per-machine cost instead of
a per-process cost, at two layers:

1. **Persistent XLA compilation cache** (:func:`configure_persistent_cache`)
   — JAX's on-disk cache keyed by the optimized HLO + compile options, under
   a framework-owned directory.  Transparent: any jit anywhere in the
   process benefits.  Hits/misses are counted through JAX's monitoring
   events (:func:`stats`).
2. **Serialized executables** (:class:`ExecutableStore`) — AOT-compiled
   ``jax.stages.Compiled`` programs (``jax.experimental
   .serialize_executable``) stored whole, keyed by a framework cache key
   (:func:`cache_key`: program tag + abstract arg signature + engine
   context) and fingerprinted by jax/jaxlib version, backend, device kind &
   count and ``XLA_FLAGS``.  A warm process skips tracing AND lowering AND
   compilation; any mismatch or load error falls back to a fresh compile
   (the cache can only ever cost a retrace, never correctness).

Engines consume both through :class:`ProgramCache` (built from the
``compile_cache`` config block, see ``docs/compile_cache.md``) and expose
``warmup()``/``precompile()`` so all shape buckets compile up front with
per-program compile times reported through the monitor.

Invalidation: executable entries are dropped (ignored) whenever the
fingerprint changes; the XLA cache is content-addressed and never stale.
Delete the cache directory to reclaim space — both layers rebuild on the
next cold run.
"""

import contextlib
import hashlib
import json
import os
import pickle
import time
from typing import Any, Dict, Optional

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel
from deepspeed_tpu.utils.logging import logger, log_dist


class CompileCacheConfig(DeepSpeedConfigModel):
    """``compile_cache`` config block (shared by the training and inference
    engines; see ``docs/compile_cache.md``)."""
    enabled: bool = False
    # framework-owned cache root; None → $DSTPU_COMPILE_CACHE_DIR or
    # ~/.cache/deepspeed_tpu/compile_cache
    cache_dir: Optional[str] = None
    # below this, XLA-cache writes are skipped (tiny programs recompile
    # faster than they deserialize); jax default is 1s
    min_compile_time_secs: float = 1.0
    # serialize/reload whole AOT executables (layer 2 above)
    executables: bool = True
    # executable store directory; None → <cache_dir>/executables
    executable_dir: Optional[str] = None


def default_cache_dir():
    return os.environ.get("DSTPU_COMPILE_CACHE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "deepspeed_tpu", "compile_cache")


# --------------------------------------------------------------------- #
# Cache-hit accounting (process-global; read deltas, not absolutes)
# --------------------------------------------------------------------- #
class CacheStats:
    """Counters for both cache layers.  ``persistent_*`` come from JAX's
    monitoring events (the on-disk XLA cache); ``executable_*`` from the
    framework's :class:`ExecutableStore`."""

    def __init__(self):
        self.persistent_requests = 0     # compiles that consulted the cache
        self.persistent_hits = 0
        self.executable_hits = 0
        self.executable_misses = 0
        self.executable_mismatches = 0   # fingerprint said "not this build"
        self.executable_saves = 0
        self.executable_errors = 0
        self.compile_seconds: Dict[str, float] = {}  # tag -> last compile time

    def snapshot(self):
        d = {k: v for k, v in self.__dict__.items()
             if isinstance(v, (int, float))}
        d["compile_seconds"] = dict(self.compile_seconds)
        return d


_STATS = CacheStats()


def stats() -> CacheStats:
    return _STATS


_listener_registered = False


def _on_jax_event(event, **kwargs):
    if event == "/jax/compilation_cache/compile_requests_use_cache":
        _STATS.persistent_requests += 1
    elif event == "/jax/compilation_cache/cache_hits":
        _STATS.persistent_hits += 1


def _register_jax_listener():
    global _listener_registered
    if _listener_registered:
        return
    try:
        from jax._src import monitoring
        monitoring.register_event_listener(_on_jax_event)
        _listener_registered = True
    except Exception as e:      # private API — accounting is best-effort
        logger.debug(f"compile-cache hit accounting unavailable: {e}")


# --------------------------------------------------------------------- #
# Layer 1: the persistent XLA compilation cache
# --------------------------------------------------------------------- #
_configured_dir = None


def configure_persistent_cache(cache_dir=None, min_compile_time_secs=None):
    """Point JAX's persistent compilation cache at a framework-owned
    directory (idempotent; process-wide).  Returns the directory."""
    global _configured_dir
    import jax
    cache_dir = cache_dir or default_cache_dir()
    os.makedirs(cache_dir, exist_ok=True)
    # the XLA cache dir is PROCESS-GLOBAL: re-pointing it (a second engine
    # with a different cache_dir, or a user-set jax_compilation_cache_dir)
    # is last-wins and fragments the cache — allowed, but never silent
    current = jax.config.jax_compilation_cache_dir
    if current not in (None, cache_dir):
        logger.warning(
            f"compile_cache: re-pointing the process-global XLA "
            f"compilation cache from {current} to {cache_dir} (the dir is "
            f"one-per-process; every engine and jit in this process now "
            f"writes there — use one cache_dir per process to avoid "
            f"fragmenting the cache)")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    if min_compile_time_secs is not None:
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_secs))
    _register_jax_listener()
    if _configured_dir is None:
        log_dist(f"persistent compilation cache at {cache_dir}", ranks=[0])
    _configured_dir = cache_dir
    return cache_dir


def _reset_jax_cache_state():
    """Drop jax's initialized-once compilation-cache module state so the
    next compile re-reads the live config.  jax 0.4.x caches the decision
    AND the cache object in module globals (``_cache_checked`` /
    ``_cache``), so flipping ``jax_compilation_cache_dir`` alone does
    NOT detach an already-used cache."""
    try:
        from jax._src import compilation_cache as jcc
        jcc.reset_cache()
        return True
    except Exception as e:                       # API drift: fail open
        logger.warning(f"compile_cache: could not reset jax's "
                       f"compilation-cache state ({e}) — persistent-cache "
                       f"suspension is best-effort only")
        return False


@contextlib.contextmanager
def suspended_persistent_cache():
    """Temporarily detach the process from the XLA persistent cache for
    the compiles inside the block (no reads, no writes).  For programs
    whose RELOADED form is unsafe to reuse across processes — the
    serving slot programs chain one donated workspace across three
    executables, and reloading ANY of them from either cache layer in a
    fresh process nondeterministically corrupts the slot cache or
    segfaults (bisected with the serving kill-harness driver; the train
    and whole-batch generate paths show no such failures and keep both
    layers).  Compiles are synchronous on the calling thread, so the
    process-global config flip is safe."""
    import jax
    prev = jax.config.jax_compilation_cache_dir
    if prev is None:
        yield
        return
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        _reset_jax_cache_state()
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
        # re-attach lazily: the next ordinary compile re-initializes
        # from the restored config
        _reset_jax_cache_state()


def deconfigure_persistent_cache():
    """Undo :func:`configure_persistent_cache` — for scripts/harnesses that
    must detach the process from a temporary cache directory before it is
    deleted (the dir is process-global; JAX would otherwise keep writing
    there)."""
    global _configured_dir
    import jax
    jax.config.update("jax_compilation_cache_dir", None)
    # the config flip alone does not detach an already-initialized cache
    # (jax caches the decision in module globals) — reset it too
    _reset_jax_cache_state()
    _configured_dir = None


# --------------------------------------------------------------------- #
# Cache keys
# --------------------------------------------------------------------- #
def runtime_fingerprint():
    """Everything that invalidates a serialized executable: compiler
    version, backend, device model & count, and compiler flags.  (The
    program itself is in the cache key, not the fingerprint.)"""
    import jax
    import jaxlib
    dev = jax.devices()[0]
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", "unknown"),
        "n_devices": jax.device_count(),
        "n_processes": jax.process_count(),
        "xla_flags": os.environ.get("XLA_FLAGS", ""),
    }


def abstract_signature(tree):
    """(shape, dtype, weak_type) of every array leaf — the shape/dtype half
    of a program's identity (topology/dtype context rides in the key
    parts).  weak_type matters: an executable compiled for a weak-typed
    scalar refuses a strong-typed one of the same dtype at call time."""
    import jax
    return tuple((tuple(l.shape), str(l.dtype),
                  bool(getattr(l, "weak_type", False)))
                 for l in jax.tree.leaves(tree) if hasattr(l, "shape"))


def cache_key(tag, *parts, fingerprint=None):
    """Stable hex key for one compiled program: tag + context parts +
    runtime fingerprint, hashed.  Parts are ``repr``'d — pass only values
    with deterministic reprs (tuples, strings, numbers, dataclasses)."""
    payload = {"tag": str(tag),
               "parts": [repr(p) for p in parts],
               "fp": fingerprint or runtime_fingerprint()}
    h = hashlib.sha256(json.dumps(payload, sort_keys=True,
                                  default=repr).encode())
    return h.hexdigest()[:40]


# --------------------------------------------------------------------- #
# Layer 2: serialized executables
# --------------------------------------------------------------------- #
class ExecutableStore:
    """On-disk store of serialized ``jax.stages.Compiled`` executables.

    Layout: ``<dir>/<key>.bin`` (pickled ``serialize_executable.serialize``
    triple) + ``<dir>/<key>.json`` (fingerprint metadata, written LAST so a
    half-written entry is never loadable).  Every failure path is a miss,
    never an error to the caller."""

    def __init__(self, directory, fingerprint=None):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._fp = fingerprint or runtime_fingerprint()

    def _paths(self, key):
        base = os.path.join(self.directory, key)
        return base + ".bin", base + ".json"

    def load(self, key):
        """Deserialized executable, or None (miss / mismatch / error)."""
        bin_path, meta_path = self._paths(key)
        if not (os.path.exists(bin_path) and os.path.exists(meta_path)):
            _STATS.executable_misses += 1
            return None
        try:
            with open(meta_path) as f:
                meta = json.load(f)
            if meta.get("fingerprint") != self._fp:
                _STATS.executable_mismatches += 1
                _STATS.executable_misses += 1
                logger.debug(
                    f"executable cache {key}: fingerprint mismatch "
                    f"(entry {meta.get('fingerprint')} vs live {self._fp}) "
                    f"— recompiling")
                return None
            with open(bin_path, "rb") as f:
                payload, in_tree, out_tree = pickle.loads(f.read())
            from jax.experimental import serialize_executable
            exe = serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree)
        except Exception as e:
            _STATS.executable_errors += 1
            _STATS.executable_misses += 1
            logger.debug(f"executable cache load failed for {key}: {e}")
            return None
        _STATS.executable_hits += 1
        return exe

    def save(self, key, compiled) -> bool:
        """Serialize + persist; atomic (tmp + rename), meta written last."""
        bin_path, meta_path = self._paths(key)
        try:
            from jax.experimental import serialize_executable
            blob = pickle.dumps(serialize_executable.serialize(compiled))
            tmp = bin_path + f".tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, bin_path)
            tmp = meta_path + f".tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"fingerprint": self._fp, "key": key,
                           "bytes": len(blob), "created": time.time()}, f)
            os.replace(tmp, meta_path)
        except Exception as e:
            _STATS.executable_errors += 1
            logger.debug(f"executable cache save failed for {key}: {e}")
            return False
        _STATS.executable_saves += 1
        return True


# --------------------------------------------------------------------- #
# Engine facade
# --------------------------------------------------------------------- #
class ProgramCache:
    """What an engine holds: the persistent-cache wiring plus (optionally)
    an executable store, with per-program compile-time accounting."""

    def __init__(self, config: CompileCacheConfig):
        self.config = config
        cache_dir = configure_persistent_cache(
            config.cache_dir, config.min_compile_time_secs)
        self.store = None
        if config.executables:
            self.store = ExecutableStore(
                config.executable_dir
                or os.path.join(cache_dir, "executables"))

    @classmethod
    def from_config(cls, config) -> Optional["ProgramCache"]:
        """None when the block is absent/disabled — engines keep the plain
        jit path untouched in that case."""
        if config is None:
            return None
        if isinstance(config, dict):
            config = CompileCacheConfig(**config)
        if not config.enabled:
            return None
        return cls(config)

    def get_or_compile(self, tag, key_parts, compile_fn):
        """Returns ``(compiled, seconds, hit)``.  ``compile_fn`` runs only
        on a store miss; its wall time is recorded under ``tag`` in
        :func:`stats` and the fresh executable is persisted."""
        key = cache_key(tag, *key_parts)
        if self.store is not None:
            exe = self.store.load(key)
            if exe is not None:
                log_dist(f"compile cache hit: {tag}", ranks=[0])
                return exe, 0.0, True
        t0 = time.perf_counter()
        exe = compile_fn()
        dt = time.perf_counter() - t0
        _STATS.compile_seconds[str(tag)] = dt
        if self.store is not None:
            self.store.save(key, exe)
        log_dist(f"compiled {tag} in {dt:.1f}s", ranks=[0])
        return exe, dt, False


def aot_compile_with_store(program_cache, tag, key_parts, fn, args):
    """Lower+compile ``fn`` for ``args`` through ``program_cache``'s
    executable store (or inline when it is None) — the one copy of the
    AOT-with-jit-fallback block all three engines share.  Returns
    ``(exe, seconds, hit)``; exe is None on any failure (warned — the
    caller runs the plain jit call, which recompiles on its own clock, so
    a failure must never masquerade as a 0.0s compile or a store hit)."""
    t0 = time.perf_counter()
    try:
        if program_cache is not None:
            return program_cache.get_or_compile(
                tag, key_parts, lambda: fn.lower(*args).compile())
        return fn.lower(*args).compile(), time.perf_counter() - t0, False
    except Exception as e:
        logger.warning(f"AOT compile of {tag} failed ({e}); falling back "
                       f"to the plain jit call")
        return None, 0.0, False
