"""Pallas decode attention — the KV-cache generation kernel.

TPU-native equivalent of the reference's ``softmax_context`` inference op
(``csrc/transformer/inference/csrc/pt_binding.cpp:1934-``; the attention
half of its decode pipeline).  Single-token decode: one query row per
(batch, head) attends over the cache.

Kernel layout: the HEAD dim rides the sublanes — per (batch, kv-head) grid
cell the query block is [G, D] (G = query heads per kv head; MHA → G per
block of heads), so the QK^T matmul is [G, D] × [D, bk] on the MXU instead
of a degenerate [1, D] row.  The KV length mask (cache tail + causality for
a single new token collapse to ``pos < length``) is applied per block, and
an online softmax accumulates across KV blocks so the cache never
materializes an S_max-wide probability row in fp32 HBM.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.transformer.flash_attention import (LSE_LANES, NEG_INF,
                                                           _interpret)

DEFAULT_BLOCK_K_DECODE = 512


def _decode_kernel(len_ref, layer_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, scale, block_k, nk, stacked):
    b = pl.program_id(0)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    length = len_ref[b]
    # skip KV blocks entirely past the live cache region
    @pl.when(ik * block_k < length)
    def _body():
        q = q_ref[0, 0]                                  # [G, D]
        k = k_ref[0, 0, 0] if stacked else k_ref[0, 0]   # [bk, D]
        v = v_ref[0, 0, 0] if stacked else v_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        pos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)                  # [1, bk]
        s = jnp.where(pos < length, s, NEG_INF)          # cache tail mask
        m_prev = m_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(pos < length, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:] = jnp.broadcast_to(
            l_scr[:, 0:1] * corr + jnp.sum(p, axis=1, keepdims=True),
            l_scr.shape)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(ik == nk - 1)
    def _finish():
        l = l_scr[:, 0:1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)


def decode_attention(q, k_cache, v_cache, lengths,
                     scale=None, block_k=DEFAULT_BLOCK_K_DECODE, layer=None):
    """Single-token decode attention.

    q: [B, H, D] (this step's query); caches: [B, KVH, S_max, D]
    (head-major — the model stores them this way so NO cache relayout
    happens per decode step), or the FULL layer-stacked
    [L, B, KVH, S_max, D] cache with ``layer`` a (traced) layer index —
    the kernel's index maps then DMA only this layer's blocks, so the
    caller never materializes a per-layer slice of the stacked cache.
    lengths: [B] int32 — number of valid cache entries INCLUDING this
    step's freshly-written position.  Returns [B, H, D].
    """
    B, H, D = q.shape
    stacked = k_cache.ndim == 5
    if stacked and layer is None:
        raise ValueError("stacked [L, ...] caches require layer=")
    KVH, S_max = k_cache.shape[-3], k_cache.shape[-2]
    G = H // KVH                                         # query heads per kv head
    if scale is None:
        scale = 1.0 / float(np.sqrt(D))
    block_k = min(block_k, S_max)
    nk = pl.cdiv(S_max, block_k)
    qg = q.reshape(B, KVH, G, D)
    layer_arr = jnp.asarray([layer if layer is not None else 0], jnp.int32)

    def _live_block(ik, lens, b):
        # pin indices past the live cache region to the last live block:
        # Mosaic skips the DMA when a block index repeats, so dead-region
        # grid steps fetch nothing (their compute is pl.when-gated off too)
        last = jnp.maximum((lens[b] + block_k - 1) // block_k - 1, 0)
        return jnp.minimum(ik, last)

    if stacked:
        kv_spec = pl.BlockSpec(
            (1, 1, 1, block_k, D),
            lambda b, h, ik, lens, li: (li[0], b, h,
                                        _live_block(ik, lens, b), 0))
    else:
        kv_spec = pl.BlockSpec(
            (1, 1, block_k, D),
            lambda b, h, ik, lens, li: (b, h, _live_block(ik, lens, b), 0))

    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=float(scale),
                          block_k=block_k, nk=nk, stacked=stacked),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, KVH, nk),
            in_specs=[
                pl.BlockSpec((1, 1, G, D),
                             lambda b, h, ik, lens, li: (b, h, 0, 0)),
                kv_spec,
                kv_spec,
            ],
            out_specs=pl.BlockSpec((1, 1, G, D),
                                   lambda b, h, ik, lens, li: (b, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((G, LSE_LANES), jnp.float32),
                pltpu.VMEM((G, LSE_LANES), jnp.float32),
                pltpu.VMEM((G, D), jnp.float32),
            ]),
        out_shape=jax.ShapeDtypeStruct((B, KVH, G, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(jnp.asarray(lengths, jnp.int32), layer_arr, qg, k_cache, v_cache)
    return out.reshape(B, H, D)
