"""Wall-clock + throughput timers.

Parity with reference ``utils/timer.py`` (``SynchronizedWallClockTimer:33``,
``ThroughputTimer:137``).  CUDA events become device-sync barriers
(XLA dispatch is async, so we synchronize before reading the clock)."""

import time

from deepspeed_tpu.utils.logging import log_dist

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"


class SynchronizedWallClockTimer:

    class Timer:

        def __init__(self, name):
            self.name_ = name
            self.started_ = False
            self.elapsed_ = 0.0
            self.start_time = 0.0
            self.records = []

        def _sync(self):
            from deepspeed_tpu.accelerator import get_accelerator
            get_accelerator().synchronize()

        def start(self, sync=True):
            if self.started_:
                return
            if sync:
                self._sync()
            self.start_time = time.perf_counter()
            self.started_ = True

        def stop(self, sync=True, record=True):
            if not self.started_:
                return
            if sync:
                self._sync()
            delta = time.perf_counter() - self.start_time
            self.elapsed_ += delta
            if record:
                self.records.append(delta)
            self.started_ = False

        def elapsed(self, reset=True):
            val = self.elapsed_
            if reset:
                self.elapsed_ = 0.0
            return val

        def mean(self):
            return sum(self.records) / len(self.records) if self.records else 0.0

        def reset(self):
            self.elapsed_ = 0.0
            self.records = []
            self.started_ = False

    def __init__(self):
        self.timers = {}

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = self.Timer(name)
        return self.timers[name]

    def log(self, names, normalizer=1.0, reset=True, ranks=None):
        assert normalizer > 0.0
        parts = []
        for name in names:
            if name in self.timers:
                elapsed = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                parts.append(f"{name}: {elapsed:.2f}")
        log_dist(f"time (ms) | {' | '.join(parts)}", ranks=ranks or [0])


class ThroughputTimer:

    def __init__(self, batch_size, start_step=2, steps_per_output=50, monitor_memory=False,
                 logging_fn=None):
        self.start_time = 0
        self.end_time = 0
        self.started = False
        self.batch_size = max(batch_size, 1)
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0
        self.step_elapsed_time = 0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or log_dist

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def start(self):
        self.started = True
        if self.global_step_count >= self.start_step:
            # no device synchronize here: a per-step sync serializes the
            # dispatch pipeline (and through a remote tunnel costs a full
            # round-trip).  Async dispatch self-throttles over a window, so
            # windowed wall-clock throughput stays accurate without syncs.
            self.start_time = time.perf_counter()

    def stop(self, global_step=False, report_speed=True):
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if global_step:
            self.global_step_count += 1
        if self.start_time > 0 and self.global_step_count >= self.start_step:
            self.end_time = time.perf_counter()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            self.step_elapsed_time += duration
            if global_step and report_speed and \
                    self.global_step_count % self.steps_per_output == 0:
                self.logging(
                    f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                    f"global_step={self.global_step_count}, "
                    f"RunningAvgSamplesPerSec={self.avg_samples_per_sec():.4f}, "
                    f"CurrSamplesPerSec={self.batch_size / max(self.step_elapsed_time, 1e-9):.4f}")
                self.step_elapsed_time = 0
            elif global_step:
                self.step_elapsed_time = 0

    def avg_samples_per_sec(self):
        if self.global_step_count > self.start_step:
            samples = self.batch_size * (self.global_step_count - self.start_step)
            return samples / max(self.total_elapsed_time, 1e-9)
        return 0.0
