"""Host-side span tracing + fixed-bucket latency histograms for the
serving stack (``docs/observability.md``) — the per-request half of the
monitor layer the reference framework ships as ``deepspeed/monitor/``.

Two primitives, both pure host bookkeeping (zero jitted programs, zero
device syncs — the overhead contract the serving engine's
zero-new-executables proof extends over them):

* :class:`SpanTracer` — a bounded ring of finished spans recorded at the
  serving scheduler's existing seams (submit → queue wait → prefill
  chunks → admit dispatch → decode / spec-propose / spec-verify
  dispatches → terminal), each stamped with BOTH the monotonic clock
  (durations, breakdowns) and the wall clock (cross-process
  correlation).  :meth:`SpanTracer.to_chrome` renders the ring as
  Chrome trace-event JSON (the ``traceEvents`` array of ``"X"``
  complete events plus ``"M"`` thread-name metadata), loadable in
  Perfetto / ``chrome://tracing`` with one track per KV slot plus
  scheduler/queue/handler tracks.
* :class:`Histogram` / :class:`HistogramFamily` /
  :class:`ServingHistograms` — fixed-bucket Prometheus histograms
  (cumulative ``_bucket{le=...}`` counts, ``_sum``, ``_count``) for
  TTFT, time-between-tokens, queue wait, per-program dispatch duration
  and engine-lock wait.  Buckets are FIXED at construction so the
  exposition never allocates on the observe path; ``observe`` takes a
  plain ``threading.Lock`` (never the engine lock — the hot path must
  not contend it).

The tracer's clock is injectable (``clock=``) so tests can drive TTFT /
TBT measurement deterministically; timestamps are stamped ONCE at the
host-mirror drain point, so a late-attached ``TokenStream`` replay can
never re-stamp them and skew the histograms.
"""

import json
import threading
import time
from collections import deque

# Default span-ring bound: ~7 spans per request-lifetime plus 1-3 per
# dispatch; 100k spans ≈ tens of MB and hours of light traffic.
DEFAULT_MAX_SPANS = 100_000

# Latency bucket bounds (seconds) — shared by the TTFT / TBT /
# queue-wait / dispatch-duration histograms.  Fixed so dashboards can
# diff rounds; spans sub-ms host dispatch up to the 60 s shed horizon.
LATENCY_BUCKETS_S = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

# Lock-wait buckets (seconds) — contention lives orders of magnitude
# below request latency; the 1 µs floor resolves uncontended acquires.
LOCK_WAIT_BUCKETS_S = (1e-6, 1e-5, 1e-4, 1e-3, 0.01, 0.1, 1.0)

# The histogram series the serving engine exports through ``/metrics``
# when ``serving.tracing`` is on.  PURE LITERAL: ``ds_lint
# --stats-docs`` parses this tuple statically (never imports the
# module) to assert every series is documented in
# ``docs/observability.md``.
HISTOGRAM_SERIES = (
    "dstpu_serving_ttft_seconds",
    "dstpu_serving_tbt_seconds",
    "dstpu_serving_queue_wait_seconds",
    "dstpu_serving_dispatch_seconds",
    "dstpu_serving_lock_acquire_wait_seconds",
)


class Histogram:
    """One fixed-bucket Prometheus histogram.  ``observe`` is safe from
    any thread (its own tiny lock, never the engine lock); ``collect``
    returns the cumulative exposition samples."""

    __slots__ = ("buckets", "counts", "sum", "count", "_lock")

    def __init__(self, buckets):
        self.buckets = tuple(float(b) for b in buckets)
        assert list(self.buckets) == sorted(self.buckets), \
            "histogram buckets must be ascending"
        self.counts = [0] * len(self.buckets)     # per-bucket (not cum.)
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, v):
        v = float(v)
        with self._lock:
            self.sum += v
            self.count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    break

    def collect(self, labels=None):
        """``[(suffix, extra_labels, value), ...]`` exposition samples —
        cumulative ``_bucket`` counts (incl. ``+Inf``), ``_sum``,
        ``_count``.  ``labels``: dict merged into every sample."""
        base = dict(labels or {})
        with self._lock:
            counts = list(self.counts)
            total, s = self.count, self.sum
        out, cum = [], 0
        for b, c in zip(self.buckets, counts):
            cum += c
            out.append(("_bucket", {**base, "le": repr(b)}, cum))
        out.append(("_bucket", {**base, "le": "+Inf"}, total))
        out.append(("_sum", base, s))
        out.append(("_count", base, total))
        return out

    def snapshot(self):
        with self._lock:
            return {"count": self.count, "sum": self.sum,
                    "counts": list(self.counts)}


class HistogramFamily:
    """Same-bucket histograms keyed by one label value (e.g. the
    dispatch program name).  Children are created lazily under the
    family lock; each child observes under its own."""

    def __init__(self, label, buckets):
        self.label = label
        self.buckets = tuple(buckets)
        self._children = {}
        self._lock = threading.Lock()

    def child(self, value):
        value = str(value)
        h = self._children.get(value)
        if h is None:
            with self._lock:
                h = self._children.setdefault(value,
                                              Histogram(self.buckets))
        return h

    def observe(self, value, v):
        self.child(value).observe(v)

    def collect(self):
        with self._lock:
            items = sorted(self._children.items())
        out = []
        for value, h in items:
            out.extend(h.collect(labels={self.label: value}))
        return out


class ServingHistograms:
    """The serving engine's histogram set (``serving.tracing``),
    exported through ``/metrics`` as the :data:`HISTOGRAM_SERIES`
    families.  All internally locked — the HTTP scrape thread never
    takes the engine lock to render them."""

    def __init__(self):
        self.ttft = Histogram(LATENCY_BUCKETS_S)
        self.tbt = Histogram(LATENCY_BUCKETS_S)
        self.queue_wait = Histogram(LATENCY_BUCKETS_S)
        self.dispatch = HistogramFamily("program", LATENCY_BUCKETS_S)
        self.lock_wait = HistogramFamily("thread_class",
                                         LOCK_WAIT_BUCKETS_S)

    def collect(self):
        """``[(series_name, help, samples), ...]`` for the Prometheus
        renderer; ``samples`` are ``(suffix, labels, value)``."""
        return [
            ("dstpu_serving_ttft_seconds",
             "submit-to-first-token wall time per request",
             self.ttft.collect()),
            ("dstpu_serving_tbt_seconds",
             "time between consecutive committed tokens, per request",
             self.tbt.collect()),
            ("dstpu_serving_queue_wait_seconds",
             "submit-to-admission-start wait per request",
             self.queue_wait.collect()),
            ("dstpu_serving_dispatch_seconds",
             "host dispatch duration per program",
             self.dispatch.collect()),
            ("dstpu_serving_lock_acquire_wait_seconds",
             "per-acquire engine-lock wait by thread class",
             self.lock_wait.collect()),
        ]


class SpanTracer:
    """Bounded ring of finished spans with Chrome trace-event export.

    ``add`` records one complete span (``t1=None`` = instant event);
    timestamps come from :meth:`now` — the injectable monotonic clock —
    and the wall-clock epoch of the tracer's construction anchors the
    export.  The caller provides external synchronization for ``add``
    (the serving engine records lock-held); ``to_chrome``/``dump`` take
    a point-in-time copy."""

    def __init__(self, max_spans=DEFAULT_MAX_SPANS, clock=time.monotonic,
                 wallclock=time.time):
        self._clock = clock
        self._t0 = clock()               # monotonic epoch
        self.wall_t0 = wallclock()       # wall-clock anchor of _t0
        self._spans = deque(maxlen=int(max_spans))
        self.added = 0                   # total, incl. ring-dropped

    def now(self):
        """The tracer's monotonic clock (injectable for tests)."""
        return self._clock()

    def add(self, name, cat, t0, t1=None, track="scheduler", **args):
        """Record one finished span: ``[t0, t1]`` on ``track`` (a slot
        id int or a named thread track), with ``args`` attached
        (rid/client_id/slot/priority/phase...).  ``None`` args are
        dropped so exports stay compact."""
        self.added += 1
        self._spans.append(
            (name, cat, float(t0),
             None if t1 is None else float(t1), track,
             {k: v for k, v in args.items() if v is not None}))

    @property
    def dropped(self):
        return self.added - len(self._spans)

    def span_snapshot(self):
        """A point-in-time ``(spans, added)`` copy of the span ring —
        take it under whatever lock guards ``add`` (the serving
        engine's), then render/serialize OUTSIDE it:
        :meth:`to_chrome`/:meth:`dump` on a 100k-span ring build tens
        of MB of JSON, far too long to stall the scheduler for.  The
        paired ``added`` counter keeps the export's ``dropped`` figure
        consistent with the copy: spans recorded AFTER the snapshot
        must not read as ring-dropped."""
        return list(self._spans), self.added

    def to_chrome(self, spans=None):
        """The Chrome trace-event JSON object (``{"traceEvents": [...]}``
        — the Perfetto-loadable format): one ``pid``, a ``tid`` per
        track (scheduler / queue / handler threads, then one per slot),
        ``"X"`` complete events in microseconds, ``"M"`` thread-name
        metadata, and the wall-clock anchor under ``otherData``.
        ``spans``: a :meth:`span_snapshot` tuple taken lock-held;
        ``None`` copies the live ring (single-threaded callers
        only)."""
        spans, added = self.span_snapshot() if spans is None else spans
        tids, events = {}, []

        def tid_for(track):
            t = tids.get(track)
            if t is None:
                t = tids[track] = len(tids)
                name = f"slot {track}" if isinstance(track, int) \
                    else str(track)
                events.append({"ph": "M", "pid": 1, "tid": t,
                               "name": "thread_name",
                               "args": {"name": name}})
            return t

        # stable track order: the named threads first, slots ascending
        for track in ("scheduler", "queue"):
            tid_for(track)
        for track in sorted({s[4] for s in spans
                             if isinstance(s[4], int)}):
            tid_for(track)
        for name, cat, t0, t1, track, args in spans:
            ev = {"name": name, "cat": cat, "pid": 1,
                  "tid": tid_for(track),
                  "ts": round((t0 - self._t0) * 1e6, 3)}
            if t1 is None:
                ev["ph"] = "i"
                ev["s"] = "t"            # thread-scoped instant
            else:
                ev["ph"] = "X"
                ev["dur"] = round(max(t1 - t0, 0.0) * 1e6, 3)
            if args:
                ev["args"] = args
            events.append(ev)
        return {"traceEvents": events,
                "displayTimeUnit": "ms",
                "otherData": {"wall_t0": self.wall_t0,
                              "spans": len(spans),
                              "dropped": added - len(spans)}}

    def dump(self, path, spans=None):
        """Write :meth:`to_chrome` to ``path``; returns ``path``."""
        with open(path, "w") as f:
            json.dump(self.to_chrome(spans=spans), f)
        return path


__all__ = ["SpanTracer", "Histogram", "HistogramFamily",
           "ServingHistograms", "LATENCY_BUCKETS_S",
           "LOCK_WAIT_BUCKETS_S", "HISTOGRAM_SERIES",
           "DEFAULT_MAX_SPANS"]
