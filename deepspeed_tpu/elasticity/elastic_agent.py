"""Elastic agent — reference ``elasticity/elastic_agent.py:28``
(``DSElasticAgent(LocalElasticAgent)``): monitor workers, patch their env,
restart on membership change.

TPU redesign: there is no per-GPU worker process to babysit — the membership
event is a *slice preemption* (SIGTERM from the TPU runtime / maintenance
event).  The agent wraps the training loop in-process: it installs signal
handlers, triggers an emergency checkpoint on preemption, and on restart
recomputes a batch-size-compatible config for the new slice size via the
elasticity solver (``compute_elastic_config``), preserving the global batch
exactly like the reference's v0.1/v0.2 schedulers.
"""

import os
import signal
import time

from deepspeed_tpu.elasticity.elasticity import compute_elastic_config
from deepspeed_tpu.utils.logging import logger


class DSElasticAgent:

    def __init__(self, ds_config, checkpoint_dir=None, checkpoint_fn=None,
                 world_size=None):
        self.ds_config = ds_config
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_fn = checkpoint_fn
        self._preempted = False
        self._prev_handlers = {}
        if world_size is None:
            import jax
            world_size = jax.device_count()
        self.world_size = world_size

    # ---------------------------------------------------------------- #
    def elastic_config_for(self, num_devices):
        """Batch-size-preserving config for a new slice size (reference
        ``compute_elastic_config``/``_get_compatible_gpus``)."""
        gbs, _, mbs = compute_elastic_config(self.ds_config,
                                             world_size=num_devices,
                                             return_microbatch=True)
        cfg = dict(self.ds_config)
        cfg["train_micro_batch_size_per_gpu"] = mbs
        cfg["gradient_accumulation_steps"] = gbs // (mbs * num_devices)
        cfg["train_batch_size"] = gbs
        return cfg

    # ---------------------------------------------------------------- #
    def _handler(self, signum, frame):
        logger.warning(f"elastic agent: received signal {signum} — "
                       "marking preemption, checkpoint on next boundary")
        self._preempted = True

    def start(self):
        """Install preemption handlers (reference patches worker env +
        monitors; TPU preemption arrives as SIGTERM)."""
        for sig in (signal.SIGTERM, signal.SIGINT):
            self._prev_handlers[sig] = signal.signal(sig, self._handler)
        return self

    def stop(self):
        for sig, h in self._prev_handlers.items():
            signal.signal(sig, h)
        self._prev_handlers = {}

    @property
    def preempted(self):
        return self._preempted

    def checkpoint_if_preempted(self, engine, tag=None):
        """Call at every step boundary: on a pending preemption, write the
        emergency checkpoint and return True (caller should exit)."""
        if not self._preempted:
            return False
        if self.checkpoint_fn is not None:
            self.checkpoint_fn()
        elif self.checkpoint_dir is not None:
            engine.save_checkpoint(self.checkpoint_dir,
                                   tag=tag or f"preempt_{int(time.time())}")
        logger.warning("elastic agent: emergency checkpoint complete")
        return True

    # ---------------------------------------------------------------- #
    def run(self, train_step_fn, engine, max_steps=None):
        """Reference ``_invoke_run``: loop the training fn, watching for
        membership changes; returns ('preempted'|'done', steps_run)."""
        self.start()
        steps = 0
        try:
            while max_steps is None or steps < max_steps:
                train_step_fn()
                steps += 1
                if self.checkpoint_if_preempted(engine):
                    return "preempted", steps
        finally:
            self.stop()
        return "done", steps
