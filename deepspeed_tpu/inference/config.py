"""Inference config — parity with reference ``inference/config.py``
(``DeepSpeedInferenceConfig``).  Same key names; CUDA-graph knobs map to
"always jitted" (every decode step is a compiled XLA program, which is what
CUDA graphs approximate on GPU)."""

from typing import Any, Dict, Optional

from pydantic import Field

from deepspeed_tpu.inference.serving.config import ServingConfig
from deepspeed_tpu.runtime.compile_cache import CompileCacheConfig
from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel
from deepspeed_tpu.runtime.fault.config import FaultConfig

# Canonical dtype-string spellings ("torch.float16", "fp16", "half", ... →
# "float16"); shared by init_inference's conversion and the engine's cast.
_DTYPE_ALIASES = {"float16": "float16", "fp16": "float16", "half": "float16",
                  "bfloat16": "bfloat16", "bf16": "bfloat16",
                  "float32": "float32", "fp32": "float32",
                  "float": "float32"}


def normalize_dtype_str(dtype) -> str:
    key = str(dtype).replace("torch.", "")
    if key not in _DTYPE_ALIASES:
        raise ValueError(f"unsupported dtype {dtype!r}; one of "
                         f"{sorted(set(_DTYPE_ALIASES))}")
    return _DTYPE_ALIASES[key]


class DeepSpeedTPConfig(DeepSpeedConfigModel):
    enabled: bool = True
    tp_size: int = 1
    mpu: Any = None
    tp_group: Any = None


class QuantizationConfig(DeepSpeedConfigModel):
    enabled: bool = False
    bits: int = 8
    group_size: int = 64
    # per-output-channel scales (int8 only): the dequant is a bare
    # convert×broadcast that XLA fuses into the consuming matmul, so decode
    # streams int8 weights from HBM (groupwise reshape chains materialize a
    # bf16 copy of every weight each decode step instead)
    per_channel: bool = False
    # int8 KV cache (TransformerConfig.kv_cache_quant): independent of
    # weight quantization — applied to the model config by init_inference
    # for models whose config carries the knob
    kv_cache: bool = False


class DeepSpeedInferenceConfig(DeepSpeedConfigModel):
    kernel_inject: bool = Field(True, alias="replace_with_kernel_inject")
    dtype: str = "bfloat16"
    tensor_parallel: DeepSpeedTPConfig = Field(
        default_factory=DeepSpeedTPConfig, alias="tp")
    mp_size: Optional[int] = None          # legacy alias for tp_size
    max_out_tokens: int = Field(1024, alias="max_tokens")
    min_out_tokens: int = 1
    max_batch_size: int = 8
    replace_method: str = "auto"
    enable_cuda_graph: bool = True         # = jitted decode step (always on)
    checkpoint: Optional[Any] = None
    base_dir: str = ""
    quant: QuantizationConfig = Field(default_factory=QuantizationConfig)
    moe: Dict[str, Any] = Field(default_factory=dict)
    ep_size: int = 1
    injection_policy: Optional[Dict] = None
    return_tuple: bool = True
    triangular_masking: bool = True
    # serving-config guardrail (reference analog: workspace-size checks in
    # inference_context.h): at compile time, compare the generation
    # program's argument+temp bytes against this fraction of device memory
    # — near/above it XLA silently switches to staging buffers and decode
    # collapses nonlinearly (measured 8x; docs/performance.md "measure the
    # cliff").  Warn above the fraction; refuse when ``strict_memory``.
    memory_guard_fraction: float = 0.85
    strict_memory: bool = False
    # chunked prefill ("auto" | int chunk | None): bounds per-layer prefill
    # transients to O(batch x chunk) via the Pallas chunk kernel — the
    # big-batch / long-prompt serving enabler (Transformer.prefill_chunked)
    prefill_chunk_size: Optional[Any] = "auto"
    # persistent compile/executable cache (runtime/compile_cache.py,
    # docs/compile_cache.md): same block shape as the training config's
    compile_cache: CompileCacheConfig = Field(
        default_factory=CompileCacheConfig)
    # fault tolerance / graceful degradation (runtime/fault/,
    # docs/fault_tolerance.md): same block shape as the training
    # config's.  ``enabled`` + ``max_retries`` bound-retry transient
    # executable-load failures; ``enabled`` + ``bucket_downshift`` turns
    # a strict_memory guard refusal into a batch split (see generate())
    fault: FaultConfig = Field(default_factory=FaultConfig)
    # continuous-batching serving (inference/serving/, docs/serving.md):
    # slot-based in-flight batching behind ``engine.serve()`` — default
    # off = current whole-batch generate() behavior.  The block also
    # carries the serving SLO knobs (deadlines, bounded-queue
    # backpressure, circuit breaker, drain timeout/budget — the
    # "Robustness & SLOs" section of docs/serving.md) and the
    # observability knobs (span tracing, flight recorder, histogram
    # metrics, profile endpoint — docs/observability.md)
    serving: ServingConfig = Field(default_factory=ServingConfig)
    # decode loop form: True (default) runs the generation decode loop as
    # a bounded lax.while_loop that stops once every row hit EOS (short
    # completions skip the masked tail steps); False keeps the fixed-
    # length lax.scan.  Tokens are bitwise-identical either way.
    decode_early_exit: bool = True

    def model_post_init(self, _ctx):
        if self.mp_size is not None and self.tensor_parallel.tp_size == 1:
            self.tensor_parallel.tp_size = self.mp_size
