"""End-to-end data pipeline: indexed dataset → DataAnalyzer → curriculum
sampler → engine training (the reference's data-efficiency loop,
``runtime/data_pipeline`` wired together)."""

import numpy as np

import jax
import jax.numpy as jnp
import flax.linen as nn

import deepspeed_tpu
from deepspeed_tpu.runtime.data_pipeline.data_sampler import (DataAnalyzer,
                                                              DeepSpeedDataSampler)
from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import (
    CurriculumScheduler)
from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import (
    MMapIndexedDataset, MMapIndexedDatasetBuilder)


class TinyLM(nn.Module):
    @nn.compact
    def __call__(self, batch):
        ids = batch["input_ids"]
        h = nn.Embed(64, 32, param_dtype=jnp.float32)(ids)
        h = nn.relu(nn.Dense(32)(h))
        logits = nn.Dense(64)(h)
        tgt = jnp.pad(ids[:, 1:], ((0, 0), (0, 1)))
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits)
                                 * jax.nn.one_hot(tgt, 64), -1))


def test_indexed_dataset_to_curriculum_training(tmp_path):
    # 1. build a binary corpus with variable-length samples
    prefix = str(tmp_path / "corpus")
    rng = np.random.default_rng(0)
    lengths = rng.integers(4, 33, size=96)
    b = MMapIndexedDatasetBuilder(prefix, dtype=np.int32)
    for L in lengths:
        b.add_item(rng.integers(0, 64, L).astype(np.int32))
    b.finalize()
    ds = MMapIndexedDataset(prefix)

    # 2. offline difficulty analysis (seqlen metric)
    an = DataAnalyzer(ds, metric_names=["seqlen"], metric_functions=[len],
                      save_path=str(tmp_path / "metrics"), num_workers=2)
    an.run()
    s2m, _ = DataAnalyzer.load_metric(str(tmp_path / "metrics"), "seqlen")
    np.testing.assert_array_equal(s2m, lengths)

    # 3. curriculum sampler consumes the metric: early batches easy (short)
    sched = CurriculumScheduler({"min_difficulty": 8, "max_difficulty": 32,
                                 "schedule_type": "fixed_linear",
                                 "schedule_config": {"total_curriculum_step": 6,
                                                     "difficulty_step": 8}})
    sampler = DeepSpeedDataSampler(
        curriculum_scheduler=sched, total_samples=len(ds),
        micro_batch_size=8, data_parallel_rank=0, data_parallel_size=1,
        metric_values=s2m)
    it = iter(sampler)
    first_idxs = next(it)
    assert all(lengths[i] <= 8 for i in first_idxs), \
        (first_idxs, lengths[list(first_idxs)])

    # 4. engine trains on curriculum-sampled, padded batches
    engine, *_ = deepspeed_tpu.initialize(
        model=TinyLM(),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-3}}})

    def pad_batch(idxs, width=32):
        rows = [np.pad(ds[i], (0, width - len(ds[i]))) for i in idxs]
        return {"input_ids": np.stack(rows).astype(np.int32)}

    losses = []
    it = iter(sampler)
    for step in range(6):
        idxs = next(it)
        loss = engine(pad_batch(idxs))
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0], losses
