"""TL008 — lock-guarded field touched outside its lock's scope.

The serving host path is multi-threaded (one engine lock, an owner-bound
scheduler thread, condvar-blocked submits, asyncio handlers bridging in
through ``run_in_executor``), and both rounds of the PR 8 post-review
hardening were host-concurrency bugs in exactly this class: ``/metrics``
iterating fairness state while the scheduler compacted it, a blocked
submit binding itself as scheduler owner.  This rule makes the lock
discipline machine-checkable the way TL006/TL007 did for the device
programs:

* **Declaring guarded state** — either a class-body dict literal::

      class MiniEngine:
          GUARDED_FIELDS = {"_queue": "_lock", "stats": "_lock"}

  or a trailing comment on the field's initializing assignment::

      self._mirror_active = np.zeros(n, bool)   # guarded-by: _lock

  The serving engine's canonical registry lives in
  ``inference/serving/concurrency.py`` (``GUARDED_FIELDS`` /
  ``LOCK_ALIASES`` — pure literals this rule parses statically, never
  imports) and is merged into every module's local declarations, so
  cross-module accesses like the HTTP front end reading ``srv.stats``
  are checked too.

* **What counts as holding the lock** — the access sits lexically inside
  ``with self._lock:`` (or a declared alias such as the engine's
  ``_cond`` condvar, detected from ``self._cond =
  threading.Condition(self._lock)``), OR the enclosing method is
  annotated ``# lock-held: _lock`` on its ``def``/decorator line —
  the documented caller-holds-the-lock contract (``_step_locked`` and
  friends).  ``__init__`` is exempt: constructor state is unshared.

* **Scope** — ``self.<field>`` accesses are checked inside the declaring
  class anywhere; ``<name>.<field>`` accesses (``srv.stats``) are
  checked in modules under the serving package or carrying a
  ``# tpu-lint: concurrency-scope`` marker, guarded by a matching
  ``with <name>.<lock>:``.

Suppress deliberate unlocked reads with the usual escape hatch and a
reason (``# tpu-lint: disable=TL008 -- reason``).  The runtime
counterpart is ``DSTPU_CONCURRENCY_CHECKS=1`` + the interleaving stress
harness (``tools/lint/interleave_check.py``) — see
``docs/tpu_lint.md`` "Concurrency contracts".
"""

import ast
import os
import re

from deepspeed_tpu.tools.lint.core import Finding, dotted_name, rule

GUARD_COMMENT_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")
LOCK_HELD_RE = re.compile(r"#\s*lock-held:\s*([A-Za-z_]\w*)")
SCOPE_MARKER = "tpu-lint: concurrency-scope"

_canonical_cache = None


def canonical_registry():
    """(guarded, aliases, locked_methods, owner_bound) statically parsed
    from the serving package's ``concurrency.py`` registry — the
    literals are read with ``ast.literal_eval``; the module is NEVER
    imported (the linter stays import-free of the code under
    analysis)."""
    global _canonical_cache
    if _canonical_cache is not None:
        return _canonical_cache
    here = os.path.dirname(os.path.abspath(__file__))
    pkg = os.path.dirname(os.path.dirname(os.path.dirname(here)))
    path = os.path.join(pkg, "inference", "serving", "concurrency.py")
    guarded, aliases, locked, owner = {}, {}, (), ()
    try:
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=path)
        for node in tree.body:
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if not isinstance(tgt, ast.Name):
                    continue
                try:
                    value = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    continue
                if tgt.id == "GUARDED_FIELDS":
                    guarded = value
                elif tgt.id == "LOCK_ALIASES":
                    aliases = value
                elif tgt.id == "LOCKED_METHODS":
                    locked = tuple(value)
                elif tgt.id == "OWNER_BOUND_METHODS":
                    owner = tuple(value)
    except OSError:
        pass                             # registry absent: local-only mode
    _canonical_cache = (guarded, aliases, locked, owner)
    return _canonical_cache


def _local_declarations(module):
    """Per-module guarded declarations: {class: {field: lock}} from
    class-body ``GUARDED_FIELDS`` dict literals and ``# guarded-by:``
    assignment comments, plus {class: {alias: lock}} condvar aliases
    (``self._cond = threading.Condition(self._lock)``)."""
    declared, aliases = {}, {}
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        fields = {}
        for stmt in node.body:
            if isinstance(stmt, ast.Assign) \
                    and any(isinstance(t, ast.Name)
                            and t.id == "GUARDED_FIELDS"
                            for t in stmt.targets):
                try:
                    value = ast.literal_eval(stmt.value)
                except (ValueError, SyntaxError):
                    continue
                if isinstance(value, dict):
                    fields.update(value)
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Assign):
                continue
            comment = module.lines[sub.lineno - 1] \
                if sub.lineno - 1 < len(module.lines) else ""
            # multi-line assignments may carry the comment on the last
            # line of the statement instead
            end = getattr(sub, "end_lineno", sub.lineno)
            tail = module.lines[end - 1] if end - 1 < len(module.lines) \
                else ""
            m = GUARD_COMMENT_RE.search(comment) \
                or GUARD_COMMENT_RE.search(tail)
            for tgt in sub.targets:
                if isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self":
                    if m:
                        fields[tgt.attr] = m.group(1)
                    if isinstance(sub.value, ast.Call) and \
                            dotted_name(sub.value.func) in (
                                "threading.Condition", "Condition") \
                            and sub.value.args \
                            and isinstance(sub.value.args[0],
                                           ast.Attribute):
                        aliases.setdefault(node.name, {})[tgt.attr] = \
                            sub.value.args[0].attr
        if fields:
            declared[node.name] = fields
    return declared, aliases


def _acceptable_locks(lock, class_aliases):
    """The lock attr plus every alias that resolves to it."""
    out = {lock}
    for alias, target in (class_aliases or {}).items():
        if target == lock:
            out.add(alias)
    return out


def _held_locks(module, fn):
    """Lock names a ``# lock-held:`` annotation on the function header
    declares as held by every caller."""
    node = fn.node
    decos = getattr(node, "decorator_list", [])
    start = min([node.lineno] + [d.lineno for d in decos])
    stop = node.body[0].lineno if node.body else node.lineno + 1
    held = set()
    # header lines only — stop BEFORE the first body statement, so a
    # docstring that merely QUOTES the convention cannot exempt a method
    for line_no in range(start, stop):
        if line_no - 1 < len(module.lines):
            m = LOCK_HELD_RE.search(module.lines[line_no - 1])
            if m:
                held.add(m.group(1))
    return held


def _own_nodes(fn_node):
    nested = set()
    for child in ast.walk(fn_node):
        if child is not fn_node and isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            nested.update(n for n in ast.walk(child) if n is not child)
    return [n for n in ast.walk(fn_node) if n not in nested]


def _parents(root):
    out = {}
    for parent in ast.walk(root):
        for child in ast.iter_child_nodes(parent):
            out[child] = parent
    return out


def _with_locks_above(node, parents, fn_node):
    """(base_dotted, lock_attr) pairs of every ``with x.y:`` item
    lexically enclosing ``node`` within the function."""
    out = []
    cur = node
    while cur in parents and cur is not fn_node:
        cur = parents[cur]
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                ctx = item.context_expr
                if isinstance(ctx, ast.Attribute):
                    base = dotted_name(ctx.value)
                    if base:
                        out.append((base, ctx.attr))
    return out


@rule("TL008", "lock-guarded field accessed outside its lock")
def check(module):
    can_guarded, can_aliases, _locked, _owner = canonical_registry()
    local_guarded, local_aliases = _local_declarations(module)
    guarded = {}
    aliases = {}
    for src_g, src_a in ((can_guarded, can_aliases),
                         (local_guarded, local_aliases)):
        for cls, fields in src_g.items():
            guarded.setdefault(cls, {}).update(fields)
        for cls, amap in src_a.items():
            aliases.setdefault(cls, {}).update(amap)
    if not guarded:
        return
    # union for non-self checks: field -> every acceptable lock attr,
    # plus the primary (non-alias) lock name for the finding's hint
    field_locks, field_primary = {}, {}
    for cls, fields in guarded.items():
        for field, lock in fields.items():
            field_primary.setdefault(field, lock)
            field_locks.setdefault(field, set()).update(
                _acceptable_locks(lock, aliases.get(cls)))
    norm = module.path.replace(os.sep, "/")
    nonself_scope = "serving" in norm or SCOPE_MARKER in module.text

    seen = set()
    for fn in module.functions:
        if fn.name == "__init__":
            continue                     # constructor state is unshared
        held = _held_locks(module, fn)
        own = _own_nodes(fn.node)
        parents = _parents(fn.node)
        cls_fields = guarded.get(fn.class_name or "", {})
        cls_aliases = aliases.get(fn.class_name or "", {})
        for node in own:
            if not isinstance(node, ast.Attribute):
                continue
            field = node.attr
            base = dotted_name(node.value)
            if base is None:
                continue
            if base == "self":
                if field not in cls_fields:
                    continue
                lock = cls_fields[field]
                ok_locks = _acceptable_locks(lock, cls_aliases)
                if held & ok_locks:
                    continue
                hint = (f"wrap in `with self.{lock}:` or annotate the "
                        f"method `# lock-held: {lock}`")
            else:
                if not nonself_scope or field not in field_locks:
                    continue
                ok_locks = field_locks[field]
                lock = field_primary[field]
                hint = f"wrap in `with {base}.{lock}:`"
            if any(b == base and attr in ok_locks
                   for b, attr in _with_locks_above(node, parents,
                                                    fn.node)):
                continue
            key = (node.lineno, base, field)
            if key in seen:
                continue
            seen.add(key)
            yield Finding(
                "TL008", module.path, node.lineno, node.col_offset,
                f"{'write' if isinstance(node.ctx, ast.Store) else 'read'}"
                f" of lock-guarded field '{base}.{field}' (guarded by "
                f"'{lock}') outside its lock scope — {hint}; a racing "
                f"scheduler thread mutates this state mid-access "
                f"(docs/tpu_lint.md 'Concurrency contracts')")
