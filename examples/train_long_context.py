"""Long-context training — the reference's sparse-attention/long-sequence
story (``docs/_tutorials/sparse-attention.md``; SURVEY §5 long-context)
rendered three ways on TPU:

* ``--attn flash``  — exact Pallas flash attention (O(S) memory);
* ``--attn bigbird`` (or fixed/longformer) — block-sparse attention via the
  sparsity-config zoo, dead blocks' DMAs skipped;
* ``--sp N``        — sequence parallelism: the sequence axis shards over
  the ``sp`` mesh axis (``ring`` KV rotation or ``ulysses`` all-to-all).

Run on a CPU dev mesh (ring attention over sp=8 at seq 2048):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu DSTPU_ACCELERATOR=cpu \
    python examples/train_long_context.py --sp 8 --seq 2048 --attn none
On the real chip (flash at seq 8192):
    python examples/train_long_context.py --seq 8192
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

# a sitecustomize may pin a hardware platform before this script runs; the
# live jax config must be updated before first device use (env is too late)
if os.environ.get("DSTPU_ACCELERATOR") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=8192)
    ap.add_argument("--attn", default="flash",
                    choices=["flash", "fixed", "bigbird", "longformer",
                             "none"])
    ap.add_argument("--sp", type=int, default=1)
    ap.add_argument("--sp_impl", default="ring",
                    choices=["ring", "ulysses"])
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--steps", type=int, default=5)
    args = ap.parse_args()

    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import Transformer, TransformerConfig

    sparse = None
    if args.attn in ("fixed", "bigbird", "longformer"):
        from deepspeed_tpu.ops.sparse_attention import (
            BigBirdSparsityConfig, BSLongformerSparsityConfig,
            FixedSparsityConfig)
        sparse = {"fixed": FixedSparsityConfig,
                  "bigbird": BigBirdSparsityConfig,
                  "longformer": BSLongformerSparsityConfig}[args.attn](
            num_heads=args.heads)

    cfg = TransformerConfig(
        vocab_size=512, hidden_size=256, num_layers=4, num_heads=args.heads,
        max_seq_len=args.seq, dtype="bfloat16",
        use_flash_attention=args.attn == "flash",
        sparse_attention=sparse,
        sequence_parallel_impl=args.sp_impl,
        # long sequences: rematerialize blocks, chunk the vocab loss
        remat=True, remat_policy="dots_and_attn_saveable",
        loss_seq_chunks=16)
    engine, *_ = deepspeed_tpu.initialize(
        model=Transformer(cfg),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1},
            "sequence_parallel": {"sp_size": args.sp},
        })
    print(f"attn={args.attn} seq={args.seq} sp={args.sp}({args.sp_impl}) "
          f"dp={engine.topology.dp}")

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, 512, (1, engine.topology.dp, args.seq)).astype(np.int32)}
    import time
    for step in range(args.steps):
        t0 = time.perf_counter()
        loss = engine.train_batch(batch=batch)
        loss = float(jax.device_get(loss))
        dt = time.perf_counter() - t0
        toks = engine.topology.dp * args.seq
        print(f"step {step}: loss {loss:.4f}  {toks/dt:,.0f} tok/s")


if __name__ == "__main__":
    main()
