"""Autotuner — finds the fastest DeepSpeed config for a model on this mesh.

Reference: ``deepspeed/autotuning/autotuner.py:42`` (``Autotuner``): a
model-info profile run (``:664``), ZeRO-stage tuning spaces (``:524``), and a
per-stage micro-batch sweep (``:741``), executed through a ResourceManager
and a grid/random/model-based tuner.

TPU-native redesign: experiments are re-jitted programs on the same mesh,
not launcher jobs.  The tuning space is pruned twice before anything runs —
an analytic ZeRO memory model first, then XLA's compile-time
``memory_analysis()`` (exact on TPU) — so OOM candidates cost a compile at
most, never a crash.  Measurements run the engine's real fused train step.
"""

import gc
import json
import os
import time

import numpy as np

from deepspeed_tpu.autotuning import constants as C
from deepspeed_tpu.autotuning.cost_model import (device_memory_limit,
                                                 estimate_zero_memory,
                                                 xla_flops_analysis,
                                                 xla_memory_analysis)
from deepspeed_tpu.autotuning.scheduler import Experiment, ResourceManager
from deepspeed_tpu.autotuning.tuner import (GridSearchTuner, ModelBasedTuner,
                                            RandomTuner)
from deepspeed_tpu.autotuning.utils import (dict_deep_update, memory_to_string,
                                            number_to_string, powers_of_two,
                                            resize_batch)
from deepspeed_tpu.utils.logging import logger


class Autotuner:
    """Sweep (zero stage × micro-batch size) on the live mesh and return the
    fastest config (reference ``Autotuner.tune``)."""

    def __init__(self,
                 model,
                 config,
                 sample_batch,
                 activation_bytes_per_sample=0,
                 measure_steps=None,
                 warmup_steps=None,
                 zero_stages=None):
        import deepspeed_tpu
        from deepspeed_tpu.runtime.config import DeepSpeedConfig

        self.model = model
        self.base_config = dict(config)
        self.sample_batch = sample_batch
        self.activation_bytes_per_sample = activation_bytes_per_sample
        self._ds = deepspeed_tpu

        parsed = DeepSpeedConfig(dict(config))
        self.at_cfg = parsed.autotuning_config
        self.metric = self.at_cfg.metric
        self.warmup_steps = (warmup_steps if warmup_steps is not None
                             else self.at_cfg.start_profile_step)
        self.measure_steps = (measure_steps if measure_steps is not None
                              else max(1, self.at_cfg.end_profile_step
                                       - self.at_cfg.start_profile_step))
        self.zero_stages = zero_stages
        self.results_dir = self.at_cfg.results_dir
        self.exps_dir = self.at_cfg.exps_dir
        self.rm = ResourceManager(self._run_experiment, exps_dir=self.exps_dir,
                                  num_workers=self.at_cfg.num_workers,
                                  exp_timeout=self.at_cfg.exp_timeout)
        self.best_exp = None
        self.best_metric_val = None
        self._model_info = None
        self._precheck_cache = {}

    # ------------------------------------------------------------------ #
    def model_info(self):
        """Parameter count/bytes from abstract init — the reference's
        model-info profile run (``autotuner.py:664``) without executing."""
        if self._model_info is None:
            import jax
            mb = resize_batch(self.sample_batch, 1)
            abstract = jax.eval_shape(
                lambda r, b: self.model.init(r, b), jax.random.key(0), mb)
            leaves = jax.tree.leaves(abstract)
            num_params = int(sum(np.prod(l.shape) for l in leaves))
            param_bytes = int(sum(np.prod(l.shape) * l.dtype.itemsize for l in leaves))
            self._model_info = {C.MODEL_INFO_NUM_PARAMS: num_params,
                                C.MODEL_INFO_PARAM_BYTES: param_bytes}
            logger.info(f"Autotuning model info: "
                        f"{number_to_string(num_params)} params "
                        f"({memory_to_string(param_bytes)})")
        return self._model_info

    # ------------------------------------------------------------------ #
    def _candidate_micro_batches(self):
        """Per-chip micro-batch candidates.  The config's
        min/max_train_batch_size bound the GLOBAL batch (mbs × gas × chips,
        same semantics as the batch triple in runtime/config.py), so divide
        by the world size and accumulation steps."""
        import jax
        denom = jax.device_count() * int(
            self.base_config.get("gradient_accumulation_steps", 1) or 1)
        lo = max(1, -(-self.at_cfg.min_train_batch_size // denom))
        hi_global = self.at_cfg.max_train_batch_size
        hi = (max(1, hi_global // denom) if hi_global
              else max(C.DEFAULT_TUNING_MICRO_BATCH_SIZES))
        cands = powers_of_two(lo, hi)
        n = self.at_cfg.num_tuning_micro_batch_sizes
        if len(cands) > n:
            # keep the largest n — big micro-batches dominate MXU utilization
            cands = cands[-n:]
        return cands

    def _generate_experiments(self):
        """Build the pruned tuning space (reference ``:524``)."""
        import jax
        info = self.model_info()
        dp = jax.device_count()
        limit = device_memory_limit()
        stages = self.zero_stages
        if stages is None:
            pinned = self.base_config.get("zero_optimization", {}).get("stage")
            stages = [pinned] if pinned is not None else [0, 1, 2, 3]
            if self.at_cfg.fast and pinned is None:
                stages = [0, 3]  # fast mode: the two ends of the memory/comm tradeoff
        exps = []
        for stage in stages:
            for mbs in self._candidate_micro_batches():
                est = estimate_zero_memory(
                    info[C.MODEL_INFO_NUM_PARAMS], dp, stage, mbs,
                    self.activation_bytes_per_sample)
                if est > limit:
                    logger.info(
                        f"Pruning z{stage}_mbs{mbs}: estimated "
                        f"{memory_to_string(est)} > limit {memory_to_string(limit)}")
                    continue
                overrides = {
                    "zero_optimization": {"stage": stage},
                    "train_micro_batch_size_per_gpu": mbs,
                }
                # keep the global batch triple consistent: drop any pinned
                # train_batch_size and let gas×mbs×dp define it
                cfg = dict_deep_update(self.base_config, overrides)
                cfg.pop("train_batch_size", None)
                cfg.setdefault("gradient_accumulation_steps", 1)
                exps.append(Experiment(f"z{stage}_mbs{mbs}", cfg))
        return exps

    # ------------------------------------------------------------------ #
    def _compile_precheck(self, mbs):
        """AOT-compile the forward loss at this micro-batch and consult XLA's
        exact memory/flops analysis (no execution).  Forward memory is a
        lower bound on train-step memory, so exceeding the budget here is a
        sound prune; returns the fwd flop count for the FLOPS metric."""
        import jax
        if mbs not in self._precheck_cache:
            micro = resize_batch(self.sample_batch, mbs * jax.device_count())
            abstract = jax.eval_shape(
                lambda r, b: self.model.init(r, b), jax.random.key(0), micro)
            try:
                compiled = jax.jit(self.model.apply).lower(abstract,
                                                           micro).compile()
                self._precheck_cache[mbs] = (xla_memory_analysis(compiled),
                                             xla_flops_analysis(compiled))
            except Exception as e:
                logger.warning(f"fwd AOT precheck failed for mbs={mbs}: {e}")
                self._precheck_cache[mbs] = (None, 0.0)
        # budget check runs on cache hits too: every zero stage at an
        # over-budget micro-batch must fail fast without recompiling
        mem, flops = self._precheck_cache[mbs]
        if mem and mem["total_bytes"] > device_memory_limit() * jax.device_count():
            raise MemoryError(
                f"XLA fwd program needs {memory_to_string(mem['total_bytes'])} "
                f"(> budget) at micro_batch={mbs}")
        return mem, flops

    def _run_experiment(self, exp):
        """Measure one candidate on the real fused train step."""
        import jax
        import jax.numpy as jnp

        cfg = dict(exp.config)
        cfg.setdefault("autotuning", {})
        if isinstance(cfg["autotuning"], dict):
            cfg["autotuning"]["enabled"] = False
        _, fwd_flops = self._compile_precheck(
            cfg.get("train_micro_batch_size_per_gpu", 1))
        engine, *_ = self._ds.initialize(model=self.model, config=cfg)
        try:
            mbs = engine.train_micro_batch_size_per_gpu()
            gas = engine.gradient_accumulation_steps()
            # micro-batch is per-chip; the engine takes the global micro batch
            micro = resize_batch(self.sample_batch, mbs * jax.device_count())
            batch = jax.tree.map(
                lambda x: np.broadcast_to(x, (gas,) + x.shape).copy(), micro)
            loss = None
            for _ in range(self.warmup_steps):
                loss = engine.train_batch(batch=batch)
            if loss is not None:
                jax.block_until_ready(loss)
            t0 = time.perf_counter()
            for _ in range(self.measure_steps):
                loss = engine.train_batch(batch=batch)
            jax.block_until_ready(loss)
            dt = time.perf_counter() - t0
            latency = dt / self.measure_steps
            throughput = engine.train_batch_size() / latency
            # FLOPS metric: fwd+bwd ≈ 3× the XLA-counted fwd flops; falls
            # back to 2·N·tokens when the backend hides cost analysis
            # (tokens per sample read off the sample batch's trailing dims)
            flops_source = "xla"
            if not fwd_flops:
                flops_source = "analytic"
                tokens_per_sample = max(
                    (int(np.prod(np.shape(l)[1:])) or 1
                     for l in jax.tree.leaves(self.sample_batch)), default=1)
                fwd_flops = 2.0 * self.model_info()[C.MODEL_INFO_NUM_PARAMS] \
                    * tokens_per_sample * mbs * jax.device_count()
            flops_per_sec = 3.0 * fwd_flops * gas / latency
            return {
                C.AUTOTUNING_METRIC_LATENCY: latency,
                C.AUTOTUNING_METRIC_THROUGHPUT: throughput,
                C.AUTOTUNING_METRIC_FLOPS: flops_per_sec,
                "flops_source": flops_source,
                "train_batch_size": engine.train_batch_size(),
                "train_micro_batch_size_per_gpu": mbs,
                "zero_stage": engine.zero_optimization_stage(),
            }
        finally:
            del engine
            gc.collect()

    # ------------------------------------------------------------------ #
    def _build_tuner(self, exps):
        t = self.at_cfg.tuner_type
        if t == C.AUTOTUNING_TUNER_RANDOM:
            return RandomTuner(exps, self.rm, self.metric)
        if t == C.AUTOTUNING_TUNER_MODELBASED:
            return ModelBasedTuner(exps, self.rm, self.metric)
        return GridSearchTuner(exps, self.rm, self.metric)

    def tune(self):
        """Run the sweep; returns the best full config dict (the artifact the
        reference writes as ``ds_config_optimal.json``)."""
        exps = self._generate_experiments()
        if not exps:
            logger.warning("Autotuning space is empty after memory pruning")
            return None
        logger.info(f"Autotuning over {len(exps)} candidate configs: "
                    + ", ".join(e.name for e in exps))
        tuner = self._build_tuner(exps)
        self.best_exp, self.best_metric_val = tuner.tune(
            # batch per round = slot count, so num_workers>1 actually
            # overlaps experiments inside schedule_experiments
            sample_size=len(self.rm.resources),
            n_trials=self.at_cfg.tuner_num_trials,
            early_stopping=self.at_cfg.tuner_early_stopping)
        self._write_results()
        return self.best_exp.config if self.best_exp else None

    # ------------------------------------------------------------------ #
    def get_best_config(self):
        return self.best_exp.config if self.best_exp else None

    def print_tuning_results(self):
        for exp in self.rm.finished_experiments:
            val = exp.results.get(self.metric)
            logger.info(f"  {exp.name}: {self.metric}="
                        f"{val if val is not None else 'FAILED: ' + str(exp.error)}")
        if self.best_exp:
            logger.info(f"Best: {self.best_exp.name} "
                        f"({self.metric}={self.best_metric_val:.3f})")

    def _write_results(self):
        os.makedirs(self.results_dir, exist_ok=True)
        summary = {
            "model_info": self.model_info(),
            "metric": self.metric,
            "best_exp": self.best_exp.to_dict() if self.best_exp else None,
            "experiments": [e.to_dict() for e in self.rm.finished_experiments],
        }
        with open(os.path.join(self.results_dir, "summary.json"), "w") as f:
            json.dump(summary, f, indent=2, default=str)
        if self.best_exp:
            with open(os.path.join(self.results_dir, "ds_config_optimal.json"), "w") as f:
                json.dump(self.best_exp.config, f, indent=2, default=str)


def autotune(model, config, sample_batch, **kwargs):
    """One-call convenience: returns the best config dict."""
    tuner = Autotuner(model, config, sample_batch, **kwargs)
    best = tuner.tune()
    tuner.print_tuning_results()
    return best
