"""RLHF actor loop sketch with the Hybrid Engine (reference
``runtime/hybrid_engine.py:32`` — DeepSpeed-Chat step 3): the SAME weights
serve fast batched generation (rollout) and ZeRO-sharded training (update),
with no reallocation between the two.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    DSTPU_ACCELERATOR=cpu python examples/rlhf_hybrid.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

# a sitecustomize may pin a hardware platform before this script runs; the
# live jax config must be updated before first device use (env is too late)
if os.environ.get("DSTPU_ACCELERATOR") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")


def main():
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import Transformer, TransformerConfig

    cfg = TransformerConfig(vocab_size=256, hidden_size=64, num_layers=2,
                            num_heads=4, max_seq_len=64, dtype="float32",
                            use_flash_attention=False)
    engine, *_ = deepspeed_tpu.initialize(
        model=Transformer(cfg),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-5}},
                "zero_optimization": {"stage": 3},
                "hybrid_engine": {"enabled": True}})

    rng = np.random.default_rng(0)
    prompts = rng.integers(0, 256, (2, 8)).astype(np.int32)
    for it in range(3):
        # rollout: batched KV-cache generation from the live training weights
        seqs = np.asarray(engine.generate(prompts, max_new_tokens=8))
        # reward + PPO loss stand-in: SFT loss on the sampled continuations
        loss = engine({"input_ids": seqs.astype(np.int32)})
        engine.backward(loss)
        engine.step()
        print(f"iter {it}: rollout {seqs.shape} loss "
              f"{float(jax.device_get(loss)):.4f}")


if __name__ == "__main__":
    main()
