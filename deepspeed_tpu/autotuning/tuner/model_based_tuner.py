"""Model-based tuner: surrogate-cost-model guided search.

The reference fits an XGBoost regressor over explored configs and picks the
unexplored config with the best predicted metric
(``deepspeed/autotuning/tuner/model_based_tuner.py``, ``tuner/cost_model.py``).
xgboost isn't in this image, so the surrogate is a ridge-regularized
least-squares model over simple config features — the same
explore-then-exploit loop, dependency-free.
"""

import numpy as np

from deepspeed_tpu.autotuning.tuner.base_tuner import BaseTuner


def _featurize(exp):
    cfg = exp.config
    mbs = cfg.get("train_micro_batch_size_per_gpu", 1) or 1
    gas = cfg.get("gradient_accumulation_steps", 1) or 1
    stage = cfg.get("zero_optimization", {}).get("stage", 0)
    remat = 1.0 if cfg.get("activation_checkpointing", {}).get(
        "partition_activations", False) else 0.0
    return np.array([1.0, np.log2(mbs), float(stage), np.log2(gas), remat])


class XGBoostCostModel:
    """Ridge-regression surrogate with the reference cost model's fit/predict
    surface (``tuner/cost_model.py:XGBoostCostModel``)."""

    def __init__(self, loss_type="reg", num_threads=None, log_interval=25,
                 upper_model=None):
        self.w = None

    def fit(self, xs, ys):
        X = np.stack(xs)
        y = np.asarray(ys, dtype=np.float64)
        lam = 1e-3
        A = X.T @ X + lam * np.eye(X.shape[1])
        self.w = np.linalg.solve(A, X.T @ y)

    def predict(self, xs):
        X = np.stack(xs)
        if self.w is None:
            return np.zeros(X.shape[0])
        return X @ self.w


class ModelBasedTuner(BaseTuner):
    """Explore ``warmup`` random configs, then repeatedly run the config the
    surrogate predicts best (reference ModelBasedTuner.find_estimated_top_configs)."""

    def __init__(self, exps, resource_manager, metric="throughput", warmup=3):
        super().__init__(exps, resource_manager, metric)
        self.warmup = warmup
        self.cost_model = XGBoostCostModel()
        self.evaluated_feats = []
        self.evaluated_metrics = []
        self._ran = 0

    def next_batch(self, sample_size=1):
        if self._ran < self.warmup or not self.evaluated_feats:
            batch = self.all_exps[:sample_size]
            self.all_exps = self.all_exps[sample_size:]
        else:
            preds = self.cost_model.predict([_featurize(e) for e in self.all_exps])
            order = np.argsort(-preds if self.maximize else preds)[:sample_size]
            batch = [self.all_exps[i] for i in order]
            for e in batch:
                self.all_exps.remove(e)
        self._ran += len(batch)
        return batch

    def update(self):
        self.evaluated_feats = []
        self.evaluated_metrics = []
        for exp in self.rm.finished_experiments:
            val = exp.results.get(self.metric)
            if val is not None:
                self.evaluated_feats.append(_featurize(exp))
                self.evaluated_metrics.append(val)
        if len(self.evaluated_feats) >= 2:
            self.cost_model.fit(self.evaluated_feats, self.evaluated_metrics)
