"""Test harness: simulate an 8-device TPU mesh on CPU.

The analog of the reference's distributed-without-a-cluster mechanism
(``tests/unit/common.py:89`` DistributedExec): instead of forking processes
per rank, JAX gives us N virtual devices in one process via
``--xla_force_host_platform_device_count`` — every sharding/collective code
path (GSPMD ZeRO, pipeline ppermute, MoE all_to_all) executes for real on the
CPU mesh.
"""

import os
import sys

# Must be set before jax *initializes a backend*.  The environment may import
# jax at interpreter start (sitecustomize) with JAX_PLATFORMS pinned to the
# real TPU platform, so overriding the env var alone is not enough — update
# the live jax config too.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ.setdefault("DSTPU_ACCELERATOR", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.device_count() == 8, f"expected 8 virtual CPU devices, got {jax.devices()}"

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_topology():
    """Each test gets a fresh global topology (the analog of tearing down
    process groups between DistributedTest cases)."""
    from deepspeed_tpu.parallel import topology
    topology.reset_topology()
    yield
    topology.reset_topology()


@pytest.fixture
def eight_devices():
    import jax
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs
