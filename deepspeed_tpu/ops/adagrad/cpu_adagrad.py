"""DeepSpeedCPUAdagrad — host (offload-tier) Adagrad, reference
``deepspeed/ops/adagrad/cpu_adagrad.py:10`` over the SIMD kernel in
``csrc/adagrad/cpu_adagrad.cpp`` (ours: ``ds_adagrad_step`` in
``csrc/adam/cpu_adam.cpp``, same vectorized design, one shared library)."""

import numpy as np

from deepspeed_tpu.ops.adam.cpu_adam import adagrad_step


class DeepSpeedCPUAdagrad:
    """Stateful host Adagrad over flat fp32 master shards (API mirrors
    ``DeepSpeedCPUAdam``: per-group in-place step with optional bf16
    copy-out for the device upload)."""

    def __init__(self, params, lr=1e-2, eps=1e-10, weight_decay=0.0):
        self.params = [np.ascontiguousarray(p, dtype=np.float32) for p in params]
        self.exp_avg_sq = [np.zeros_like(p) for p in self.params]
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self.step_count = 0

    def step(self, grads, bf16_outs=None, lr=None):
        self.step_count += 1
        lr = self.lr if lr is None else lr
        for i, (p, g) in enumerate(zip(self.params, grads)):
            out = bf16_outs[i] if bf16_outs is not None else None
            adagrad_step(p, self.exp_avg_sq[i],
                         np.ascontiguousarray(g, dtype=np.float32),
                         lr, self.eps, self.weight_decay, bf16_out=out)

    def state_dict(self):
        return {"step": self.step_count, "exp_avg_sq": self.exp_avg_sq}

    def load_state_dict(self, sd):
        self.step_count = sd["step"]
        self.exp_avg_sq = [np.ascontiguousarray(a, np.float32)
                           for a in sd["exp_avg_sq"]]
