"""Worker for the real multi-process bootstrap test (launched through
``launcher/runner.py``; see ``test_multiprocess_bootstrap.py``).

Each OS process brings ``WORKER_LOCAL_DEVICES`` virtual CPU devices; with a
``DSTPU_COORDINATOR_ADDRESS`` in the environment (injected per-host by the
launcher), ``deepspeed_tpu.init_distributed`` rendezvouses the processes via
``jax.distributed.initialize`` into one global mesh — the analog of the
reference's multi-process test harness (``tests/unit/common.py:89-186``)
and its RANK/MASTER_ADDR bootstrap (``launcher/launch.py:216``).
"""

import os
import sys

n_local = int(os.environ.get("WORKER_LOCAL_DEVICES", "4"))
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + f" --xla_force_host_platform_device_count={n_local}").strip()
os.environ["DSTPU_ACCELERATOR"] = "cpu"
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.environ["DSTPU_REPO_ROOT"])

import numpy as np
import jax

# the environment may pin a hardware platform via sitecustomize (which
# imports jax at interpreter start) — env vars alone are too late, the live
# config must be updated before any backend/distributed use
jax.config.update("jax_platforms", "cpu")

import deepspeed_tpu

deepspeed_tpu.init_distributed()

import jax.numpy as jnp  # noqa: E402  (after distributed init)
from deepspeed_tpu.models.transformer import Transformer, TransformerConfig

rank, world = jax.process_index(), jax.process_count()
print(f"[worker] process {rank}/{world}, local devices "
      f"{jax.local_device_count()}, global {jax.device_count()}", flush=True)

variant = os.environ.get("WORKER_VARIANT", "zero2")
rng = np.random.default_rng(0)
if variant == "pp":
    # pipeline over the OUTERMOST mesh axis: with 2 processes the pp
    # ppermutes cross the process boundary — the DCN-tier exchange of a
    # real multi-host pipeline (reference 3D topology maps pp to the
    # inter-node axis, runtime/pipe/topology.py)
    from deepspeed_tpu.models.pipeline_transformer import transformer_pipe
    cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=4,
                            num_heads=4, max_seq_len=32,
                            use_flash_attention=False, dtype="float32",
                            scan_layers=False, remat=False)
    engine, *_ = deepspeed_tpu.initialize(
        model=transformer_pipe(cfg),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 4,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "tensor_parallel": {"tp_size": 2},
            "pipeline": {"stages": 2, "schedule": "1f1b"},
            "seed": 0,
        })
    # microbatch dim covers micro_bs(2) x dp replicas, like the zero2 path
    batch = {"input_ids": rng.integers(
        0, 64, (4, 2 * engine.topology.edp, 16)).astype(np.int32)}
else:
    cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=2,
                            num_heads=4, max_seq_len=32,
                            use_flash_attention=False, dtype="float32",
                            scan_layers=False, remat=False)
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "seed": 0,
    }
    if variant in ("sp", "ulysses"):
        # sequence parallelism over the FULL device set (sp=8, dp=1): edp
        # is outer to sp in the mesh axis order, so only a full-width sp
        # axis actually spans both processes' devices.  "sp" = ring
        # attention (KV-rotation ppermutes cross the process boundary);
        # "ulysses" = all-to-all head scatter/gather crossing it (the
        # DeepSpeed-Ulysses exchange at DCN tier)
        import dataclasses
        impl = "ring" if variant == "sp" else "ulysses"
        # ulysses scatters heads over sp: needs num_heads % sp == 0
        heads = 4 if variant == "sp" else 8
        cfg = dataclasses.replace(cfg, sequence_parallel_impl=impl,
                                  num_heads=heads)
        config["sequence_parallel"] = {"sp_size": 8}
    elif variant == "moe":
        # expert parallelism over the FULL device set (ep=8, edp=1): the
        # MoE dispatch/combine all_to_alls cross the process boundary —
        # the reference's multi-node expert placement
        # (moe/sharded_moe.py all_to_all over the expert group)
        import dataclasses
        cfg = dataclasses.replace(cfg, scan_layers=False,
                                  moe_num_experts=8, moe_ep_size=8,
                                  moe_every=2, moe_capacity_factor=2.0)
        config["moe"] = {"ep_size": 8}
    engine, *_ = deepspeed_tpu.initialize(
        model=Transformer(cfg),
        config=config)
    # every process supplies the same global batch (single-controller-per-
    # host: the engine shards it over the global mesh)
    batch = {"input_ids": rng.integers(
        0, 64, (1, 2 * engine.topology.dp, 16)).astype(np.int32)}

# cross-world-size checkpoint flow (the reference's DistributedFixture
# pattern, tests/unit/common.py:215: produce at one world size, consume at
# another): WORKER_LOAD_DIR resumes before stepping, WORKER_SAVE_DIR
# checkpoints after the first two steps
load_dir = os.environ.get("WORKER_LOAD_DIR")
if load_dir:
    engine.load_checkpoint(load_dir)
    print(f"[worker] resumed at global_steps={engine.global_steps}",
          flush=True)

losses = []
for _ in range(2):
    loss = engine.train_batch(batch=batch)
    losses.append(float(jax.device_get(loss)))

save_dir = os.environ.get("WORKER_SAVE_DIR")
if save_dir:
    engine.save_checkpoint(save_dir)
    loss = engine.train_batch(batch=batch)   # one post-save step
    losses.append(float(jax.device_get(loss)))
print(f"[worker] rank {rank} losses: {losses}", flush=True)

out = os.environ.get("WORKER_OUT")
if out:
    with open(f"{out}.rank{rank}", "w") as f:
        f.write(" ".join(repr(l) for l in losses))
