"""The program-contract lockfile gate (``tools/lint/contract.py`` +
``PROGRAMS.lock``).

Tier-1 regenerates every contract — primitive multiset, donation-alias
count, collective counts, abstract signatures — from the REAL hot-path
programs and the ``parallel/`` sharding plans, and diffs them against the
committed lockfile: a lost donation, a new host callback, a surprise
collective, or a drifted signature fails here with a readable per-program
diff instead of surfacing as an HBM cliff rounds later."""

import json
import re
import pathlib

import pytest

from deepspeed_tpu.tools.lint import contract

HERE = pathlib.Path(__file__).resolve().parent
REPO = HERE.parents[1]
LOCK = REPO / contract.LOCKFILE_NAME

# hot-path registry names covered by a locked program contract
_COVERED = {
    "runtime.train_step": "runtime.train_step",
    "runtime.apply_update": "runtime.apply_update",
    "inference.decode": "inference.decode",
    "inference.prefill_chunk": "inference.prefill_chunk",
    "serving.decode_step": "serving.decode_step",
    "serving.admit": "serving.admit",
    "serving.decode_step_paged": "serving.decode_step_paged",
    "serving.prefill_chunk_paged": "serving.prefill_chunk_paged",
    "serving.admit_paged": "serving.admit_paged",
    "serving.spec_propose": "serving.spec_propose",
    "serving.spec_verify": "serving.spec_verify",
    "serving.spec_verify_paged": "serving.spec_verify_paged",
    "serving.spec_draft_prefill": "serving.spec_draft_prefill",
    "serving.spec_draft_admit": "serving.spec_draft_admit",
    "hybrid.rollout_generate": "hybrid.rollout",
}
# host-side orchestrators / sub-programs of a locked contract: no single
# stable jitted program of their own.  A NEW @hot_path lands in neither
# set and fails test_lockfile_covers_registered_hot_paths until its
# contract exists (or it is consciously exempted here).
_ORCHESTRATORS = {
    "runtime.train_batch",      # host loop around runtime.train_step
    "runtime.step",             # 3-call path orchestrator
    "runtime.forward",          # 3-call path orchestrator
    "runtime.fwd_bwd",          # sub-program of the fused/3-call step
    "runtime.fwd_bwd_acc",      # gas>1 variant of fwd_bwd
    "inference.generate",       # host wrapper around inference.decode
    "hybrid.rollout_cast",      # once-per-optimizer-step view builder
    # the HTTP front end's scheduler-owner loop drives the engine's
    # locked serving programs and must never mint one of its own — the
    # e2e zero-new-executables test (test_serving_frontend.py) proves it
    "serving.http_frontend_loop",
}


def _registered_hot_path_names():
    """Static sweep: every ``@hot_path("name")`` in the package source."""
    names = set()
    pkg = REPO / "deepspeed_tpu"
    for path in pkg.rglob("*.py"):
        for m in re.finditer(r'@hot_path\(\s*"([^"]+)"', path.read_text()):
            names.add(m.group(1))
    return names


@pytest.fixture(scope="module")
def lock():
    assert LOCK.exists(), \
        f"{LOCK} missing — generate with bin/ds_lint --contracts --update"
    return json.loads(LOCK.read_text())


def test_lockfile_covers_registered_hot_paths(lock):
    """Every @hot_path in the package is either contract-locked or a
    documented host orchestrator — a new hot path must add its contract
    (ds_lint --contracts --update) or a conscious exemption above."""
    registered = _registered_hot_path_names()
    registered.discard("name")           # the docstring example in hotpath.py
    unknown = registered - set(_COVERED) - _ORCHESTRATORS
    assert not unknown, \
        f"@hot_path entry point(s) with no contract in {LOCK.name}: " \
        f"{sorted(unknown)}"
    programs = lock["programs"]
    missing = {v for v in _COVERED.values()} - set(programs)
    assert not missing, f"contracts missing from {LOCK.name}: {missing}"
    # the paged serving programs are explicitly part of the acceptance bar
    for name in ("serving.decode_step_paged", "serving.prefill_chunk_paged",
                 "serving.admit_paged"):
        assert name in programs


def test_lockfile_programs_have_sound_contracts(lock):
    """Locked invariants that must hold regardless of drift: no host
    callbacks anywhere, and donated programs actually alias."""
    for name, c in lock["programs"].items():
        assert c["host_callbacks"] == 0, name
        if c["donation"]["declared"]:
            floor = c["donation"]["min_aliased"] or 1
            assert c["donation"]["aliased"] >= floor, (name, c["donation"])


@pytest.mark.parametrize("builder_name", contract.program_names())
def test_program_contract_matches_lockfile(lock, builder_name):
    """The gate: regenerate this program's contract and diff it against
    the committed lockfile — any mismatch fails with the per-program
    field diff."""
    name, fresh = contract.build_program_contract(builder_name)
    locked = lock["programs"].get(name)
    assert locked is not None, \
        f"{name} not in {LOCK.name} — run ds_lint --contracts --update"
    diff = contract.diff_program(name, locked, fresh)
    assert not diff, "contract break (regenerate-and-diff):\n" + \
        "\n".join(diff)


@pytest.mark.parametrize("plan_name",
                         [b.__name__ for b in __import__(
                             "deepspeed_tpu.parallel.plans",
                             fromlist=["PLAN_BUILDERS"]).PLAN_BUILDERS])
def test_collective_schedule_matches_lockfile(lock, plan_name):
    """The static collective-schedule gate: the sharding plan's compiled
    HLO must carry exactly the locked collective counts (and satisfy the
    plan's semantic invariants) — MULTICHIP dry-run totals are locked,
    not re-measured."""
    name, fresh = contract.build_plan_contract(plan_name)
    problems = contract.validate_plan_contract(fresh)
    assert not problems, f"{name}: {problems}"
    locked = lock["collective_schedules"].get(name)
    assert locked is not None, \
        f"{name} not in {LOCK.name} — run ds_lint --contracts --update"
    diff = contract.diff_program(name, locked, fresh)
    assert not diff, "collective-schedule break:\n" + "\n".join(diff)


# ------------------------------------------------------------------ #
# The gate actually fails, readably, on synthetic contract breaks
# ------------------------------------------------------------------ #
def _synthetic_donating_ep(donate=True):
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.tools.lint.entry_points import EntryPoint

    def update(params, cache):
        return jax.tree.map(lambda c: c + 1.0, cache)

    fn = jax.jit(update, donate_argnums=(1,)) if donate else jax.jit(update)
    args = ({"w": jnp.ones((4, 4))}, {"k": jnp.zeros((2, 8))})
    return EntryPoint("synthetic.update", fn, args, expect_donation=donate)


def test_dropped_donation_fails_with_readable_diff():
    """Acceptance: a synthetic contract break (the exact PR 5 bug class —
    a donation silently dropped) fails the diff with a per-program,
    per-field message."""
    locked = contract.contract_of_entry_point(_synthetic_donating_ep(True))
    fresh = contract.contract_of_entry_point(_synthetic_donating_ep(False))
    assert locked["donation"]["aliased"] >= 1
    assert fresh["donation"]["aliased"] == 0
    diff = contract.diff_program("synthetic.update", locked, fresh)
    text = "\n".join(diff)
    assert diff and diff[0] == "synthetic.update:"
    assert "donation" in text and "LOST donation" in text


def test_surprise_collective_and_primitive_drift_diff():
    """Tampered lockfile entries produce readable field-level diffs."""
    locked = {"kind": "collective_schedule", "mesh": {"tp": 2},
              "collectives": {"all-gather": 35, "all-reduce": 39},
              "expect": ["all-gather"], "reduction": True}
    fresh = dict(locked, collectives={"all-gather": 37, "all-reduce": 39,
                                      "all-to-all": 2})
    diff = contract.diff_program("parallel.fake", locked, fresh)
    text = "\n".join(diff)
    assert "collectives.all-gather: 35 -> 37" in text
    assert "collectives.all-to-all: 0 -> 2" in text

    # plan semantics (expect / reduction) are part of the schedule contract
    weakened = dict(locked, expect=[], reduction=False)
    text = "\n".join(contract.diff_program("parallel.fake", locked, weakened))
    assert "expect: ['all-gather'] -> []" in text
    assert "reduction: True -> False" in text

    p_locked = {"kind": "program", "primitives": {"scan": 1, "add": 3},
                "primitives_sha256": "aaaa", "host_callbacks": 0,
                "collectives": {}, "donation": {"declared": True,
                                                "aliased": 2,
                                                "min_aliased": 0},
                "in_avals": ["f32[2]"], "out_avals": ["f32[2]"]}
    p_fresh = dict(p_locked, primitives={"scan": 1, "add": 3,
                                         "pure_callback": 1},
                   primitives_sha256="bbbb", host_callbacks=1)
    diff = contract.diff_program("inference.fake", p_locked, p_fresh)
    text = "\n".join(diff)
    assert "primitives.pure_callback: 0 -> 1" in text
    assert "host_callbacks: 0 -> 1" in text


def test_diff_lockfiles_reports_added_and_removed():
    a = {"programs": {"x": {"kind": "program"}}, "collective_schedules": {}}
    b = {"programs": {"y": {"kind": "program"}}, "collective_schedules": {}}
    text = "\n".join(contract.diff_lockfiles(a, b))
    assert "x: locked but no longer extracted" in text
    assert "y: not in PROGRAMS.lock" in text
