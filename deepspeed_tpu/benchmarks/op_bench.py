"""Op-level micro-benchmarks — the analog of reference ``tests/perf/``
(``adam_test.py`` op-speed measurement) plus kernel throughput for the Pallas
hot paths.  Run as a CLI; prints one JSON line per op.

Timing protocol: the axon tunnel adds ~3ms per dispatch and
``block_until_ready`` can return early, so (a) every measurement closes with
a dependent ``device_get`` of a scalar derived from the output, and (b) the
op is iterated *inside* one compiled ``lax.fori_loop`` with a data
dependence between iterations — one dispatch amortizes the tunnel latency
across all iters and XLA cannot elide or overlap the chain.
"""

import argparse
import json
import time

import numpy as np


def _sync_scalar(x):
    from deepspeed_tpu.utils.sync import dependent_sync_scalar
    return dependent_sync_scalar(x)


def _timeit(fn, args, iters):
    """Wall-clock per call with warm-up + dependent sync (multi-dispatch —
    includes per-call tunnel latency; used where chaining is impossible)."""
    out = fn(*args)          # compile
    _sync_scalar(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _sync_scalar(out)
    return (time.perf_counter() - t0) / iters


def _timeit_chained(step, init, iters):
    """Time ``step`` (a pytree→same-shape-pytree function) applied ``iters``
    times inside one jitted ``fori_loop`` — one dispatch total."""
    import jax
    from jax import lax

    @jax.jit
    def loop(x0):
        return lax.fori_loop(0, iters, lambda i, x: step(x), x0)

    out = loop(init)         # compile + warm
    _sync_scalar(out)
    t0 = time.perf_counter()
    out = loop(init)
    _sync_scalar(out)
    return (time.perf_counter() - t0) / iters


def bench_adam(numel=50_000_000, iters=20):
    """Fused Adam update throughput (reference tests/perf/adam_test.py).
    The (params, state) chain is the natural data dependence."""
    import jax.numpy as jnp
    from deepspeed_tpu.ops.adam.fused_adam import FusedAdamW

    opt = FusedAdamW(lr=1e-4)
    params = {"w": jnp.ones((numel,), jnp.float32)}
    grads = {"w": jnp.full((numel,), 1e-3, jnp.float32)}
    state = opt.init(params)

    def step(carry):
        p, s = carry
        new_p, new_s = opt.update(grads, s, p, step=1)
        return (new_p, new_s)

    dt = _timeit_chained(step, (params, state), iters)
    # adam reads p,g,m,v and writes p,m,v: 7 fp32 streams
    gbps = 7 * numel * 4 / dt / 1e9
    return {"op": "fused_adamw", "numel": numel, "ms": round(dt * 1e3, 3),
            "effective_GB/s": round(gbps, 1)}


def bench_flash_attention(b=4, s=2048, h=16, d=64, iters=20, bwd=False):
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.transformer.flash_attention import flash_attention

    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
               for _ in range(3))
    if bwd:
        grad_fn = jax.grad(lambda q, k, v: flash_attention(
            q, k, v, causal=True).astype(jnp.float32).sum(), argnums=(0, 1, 2))

        def step(carry):
            qq, kk, vv = carry
            dq, dk, dv = grad_fn(qq, kk, vv)
            # feed grads back in as next inputs: full data dependence
            return (dq.astype(jnp.bfloat16), dk.astype(jnp.bfloat16),
                    dv.astype(jnp.bfloat16))

        dt = _timeit_chained(step, (q, k, v), iters)
    else:
        def step(carry):
            qq, kk, vv = carry
            out = flash_attention(qq, kk, vv, causal=True)
            return (out, kk, vv)

        dt = _timeit_chained(step, (q, k, v), iters)
    # causal attention flops: 2 gemms, half the square
    flops = (2 * 2 * b * h * s * s * d) / 2 * (3.5 if bwd else 1)
    return {"op": f"flash_attention_{'bwd' if bwd else 'fwd'}",
            "shape": [b, s, h, d], "ms": round(dt * 1e3, 3),
            "TFLOP/s": round(flops / dt / 1e12, 2)}


def bench_quantizer(numel=64 * 1024 * 1024, bits=8, iters=20):
    import jax.numpy as jnp
    from deepspeed_tpu.ops.quantizer.kernels import quantize, dequantize

    x = jnp.ones((numel,), jnp.bfloat16)
    groups = numel // 2048

    def step(t):
        return dequantize(*quantize(t, groups, num_bits=bits),
                          num_bits=bits).reshape(t.shape).astype(t.dtype)

    dt = _timeit_chained(step, x, iters)
    return {"op": f"quant_dequant_int{bits}", "numel": numel,
            "ms": round(dt * 1e3, 3),
            "GB/s": round(numel * 2 / dt / 1e9, 1)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", default="adam,flash_fwd,flash_bwd,quant")
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args()
    runners = {
        "adam": lambda: bench_adam(iters=args.iters),
        "flash_fwd": lambda: bench_flash_attention(iters=args.iters),
        "flash_bwd": lambda: bench_flash_attention(iters=args.iters, bwd=True),
        "quant": lambda: bench_quantizer(iters=args.iters),
    }
    for name in args.ops.split(","):
        try:
            print(json.dumps(runners[name.strip()]()))
        except Exception as e:          # keep sweeping (parity: ds_bench)
            print(json.dumps({"op": name, "error": str(e)[:200]}))


if __name__ == "__main__":
    main()
