"""Compressed (1-bit) collectives — TPU-native re-design of the reference's
cupy/NCCL compressed allreduce (``runtime/comm/nccl.py:54``
``NcclBackend.compressed_allreduce``, ``runtime/comm/mpi.py`` MpiBackend).

The algorithm (Tang et al.) is unchanged:

1. compensate: ``buf = x + worker_error``
2. worker-compress to ``sign(buf) × scale`` (scale = ‖buf‖₂/√n), update
   worker error feedback
3. exchange sign *bits* chunk-wise (all_to_all) + per-worker scales
4. server-decode: average the workers' signed chunks, compensate with the
   server error, re-compress, update server error
5. all_gather the server-compressed chunks → every worker holds the result

The NCCL igather/cupy packing machinery maps to ``lax`` collectives over a
mesh axis inside ``shard_map``, and cupy ``packbits`` to ``jnp.packbits`` —
the wire format really is 1 bit/element + one f32 scale per worker-chunk.
Over ICI this buys little (GSPMD reduces grads in hardware), so this backend
is the DCN-tier analog: compress what crosses the slow fabric.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.utils.logging import logger


def pack_signs(x):
    """bool/± tensor → uint8 bitmap (1 bit per element; length padded to 8)."""
    bits = (x >= 0).astype(jnp.uint8)
    n = bits.shape[-1]
    pad = (-n) % 8
    if pad:
        bits = jnp.pad(bits, [(0, 0)] * (bits.ndim - 1) + [(0, pad)])
    return jnp.packbits(bits, axis=-1)


def unpack_signs(packed, n):
    """uint8 bitmap → ±1.0 float tensor of length ``n``."""
    bits = jnp.unpackbits(packed, axis=-1)[..., :n]
    return bits.astype(jnp.float32) * 2.0 - 1.0


def compressed_allreduce(x, worker_error, server_error, axis):
    """1-bit compressed mean-allreduce of ``x`` over mesh axis ``axis``.

    Must run inside ``shard_map``/``pjit`` with ``axis`` bound.  ``x`` is each
    device's full local tensor (like a plain allreduce input);
    ``worker_error`` has ``x``'s (padded) flat shape, ``server_error`` is the
    per-device chunk's shape.  Returns ``(avg, new_worker_error,
    new_server_error)``.
    """
    W = lax.psum(1, axis)
    shape = x.shape
    n = int(np.prod(shape))
    chunk = -(-n // W) * W // W  # ceil to divide evenly
    n_pad = chunk * W
    flat = jnp.pad(x.astype(jnp.float32).ravel(), (0, n_pad - n))

    # 1-2. worker compression with error feedback (scale over the n REAL
    # elements — pad zeros must not dilute it)
    buf = flat + worker_error
    my_scale = jnp.linalg.norm(buf) / jnp.sqrt(float(n))
    new_worker_error = buf - my_scale * jnp.sign(buf)

    # 3. chunk-wise sign exchange: worker j receives every worker's chunk j
    packed = pack_signs(buf.reshape(W, chunk))             # [W, chunk/8] u8
    recv = lax.all_to_all(packed, axis, split_axis=0, concat_axis=0,
                          tiled=True)                      # [W, chunk/8]
    scales = lax.all_gather(my_scale, axis)                # [W]

    # 4. server decode + re-compress.  Pad elements (global index ≥ n, all in
    # the last chunk) decode as +1 bits with no compensating error feedback —
    # mask them out of the decode AND the server scale, else they bias every
    # round (sign(0)=0 never cancels a transmitted +scale)
    my_chunk_start = lax.axis_index(axis) * chunk
    valid = (my_chunk_start + jnp.arange(chunk)) < n       # [chunk]
    n_valid = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    signs = unpack_signs(recv, chunk)                      # [W, chunk] ±1
    decoded = jnp.where(valid,
                        jnp.mean(signs * scales[:, None], axis=0), 0.0)
    sbuf = decoded + server_error
    s_scale = jnp.linalg.norm(sbuf) / jnp.sqrt(n_valid)
    new_server_error = jnp.where(valid, sbuf - s_scale * jnp.sign(sbuf), 0.0)

    # 5. broadcast server-compressed chunks to everyone
    all_packed = lax.all_gather(pack_signs(sbuf[None, :])[0], axis)  # [W, chunk/8]
    all_scales = lax.all_gather(s_scale, axis)             # [W]
    out = (unpack_signs(all_packed, chunk) * all_scales[:, None]).ravel()[:n]
    return out.reshape(shape), new_worker_error, new_server_error


class CompressedBackend:
    """Stateful wrapper holding the error-feedback buffers per named tensor
    (the reference backend keeps ``worker_errors``/``server_errors`` the same
    way).  ``allreduce(name, x)`` returns the compressed-mean result; buffers
    are created lazily on first use and live on device."""

    def __init__(self, mesh, axis):
        self.mesh = mesh
        self.axis = axis
        self.worker_errors = {}
        self.server_errors = {}
        self._fns = {}

    def size(self):
        return int(np.prod([self.mesh.shape[a] for a in
                            ((self.axis,) if isinstance(self.axis, str)
                             else self.axis)]))

    def _buffers(self, name, n):
        """Error-feedback buffers, one row per device (sharded over the
        compression axis so every device owns exactly its own feedback).
        A name reused at a different size resets its feedback (it is a new
        tensor as far as the algorithm is concerned)."""
        W = self.size()
        n_pad = -(-n // W) * W
        if name in self.worker_errors and \
                self.worker_errors[name].shape[1] != n_pad:
            logger.warning(f"CompressedBackend: tensor {name!r} reused with a "
                           f"different size; resetting its error feedback")
            del self.worker_errors[name], self.server_errors[name]
        if name not in self.worker_errors:
            from jax.sharding import NamedSharding, PartitionSpec as P
            row = NamedSharding(self.mesh, P(self.axis))
            self.worker_errors[name] = jax.device_put(
                jnp.zeros((W, n_pad), jnp.float32), row)
            self.server_errors[name] = jax.device_put(
                jnp.zeros((W, n_pad // W), jnp.float32), row)
        return self.worker_errors[name], self.server_errors[name]

    def allreduce(self, name, x):
        from deepspeed_tpu.utils.jax_compat import shard_map
        from jax.sharding import PartitionSpec as P
        n = int(np.prod(x.shape))
        we, se = self._buffers(name, n)
        key = (name, x.shape, x.dtype)
        if key not in self._fns:
            axis = self.axis

            @functools.partial(
                shard_map, mesh=self.mesh,
                in_specs=(P(), P(axis), P(axis)),  # tpu-lint: disable=TL010 -- the 1-bit collective's input IS each worker's full local gradient by contract; compression + reduction happen inside, error feedback stays sharded
                out_specs=(P(), P(axis), P(axis)),
                check_vma=False)
            def fn(x, we, se):
                out, nwe, nse = compressed_allreduce(x, we[0], se[0], axis)
                return out, nwe[None, :], nse[None, :]

            self._fns[key] = jax.jit(fn)
        out, new_we, new_se = self._fns[key](x, we, se)
        self.worker_errors[name] = new_we
        self.server_errors[name] = new_se
        return out
