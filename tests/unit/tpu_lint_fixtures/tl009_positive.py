"""TL009 positive fixture — engine calls that block the asyncio loop
thread (or owner-bound calls that can never succeed there).  Expect
>= 5 findings."""
import asyncio  # noqa: F401


async def handler(srv, spec):
    rid = srv.submit(spec)               # FINDING: blocks the loop
    return rid


async def poll(srv, rid):
    return srv.status(rid)               # FINDING: blocks the loop


async def drive(srv):
    srv.step()                           # FINDING: owner-bound


async def sneaky(loop, srv):
    # even through the executor, drain() runs on a worker thread that
    # can never be the scheduler owner — it raises at runtime
    await loop.run_in_executor(None, srv.drain)   # FINDING: owner-bound


def wire(loop, srv):
    loop.call_soon_threadsafe(bad_callback, srv)


def bad_callback(srv):
    # registered on the loop via call_soon_threadsafe above: runs ON the
    # loop thread, so a lock-taking call stalls every connection
    srv.cancel(3)                        # FINDING: callback blocks loop


class LocalServer:
    GUARDED_FIELDS = {"_queue": "_lock"}

    def __init__(self):
        import threading
        self._lock = threading.RLock()
        self._queue = []

    def enqueue(self, x):
        with self._lock:
            self._queue.append(x)


async def local_handler(srv, x):
    # the module-local class's lock-taking method is derived, not listed
    srv.enqueue(x)                       # FINDING: module-derived method
