"""Slot-lane programs for the continuous-batching serving engine.

The fixed-shape contract (``docs/serving.md``): the KV workspace holds
``num_slots`` cache lanes ``[L, num_slots, cache_len, KVH*D]`` and every
piece of per-slot occupancy state (last token, write position, live flag,
steps remaining, eos id) is a TRACED argument — so admissions, EOS
retirements and request churn never change a program shape, and exactly ONE
decode-step executable serves the whole server lifetime (compiled once per
process — the serving programs bypass the persistent caches, see
``ServingEngine.__init__``).

Two programs:

* :func:`make_decode_block_fn` — the decode step.  One call advances every
  slot ``block`` tokens through the model's per-row decode path (rank-1
  ``start_pos`` selects the scatter cache write and the per-row length
  masks; free/retired lanes write masked garbage that the next occupant
  overwrites position-by-position before ever attending to it).  The cache
  AND the slot state are donated — the workspace updates in place.
* :func:`make_admit_fn` — admission, fused into one dispatch: sample the
  first token from the prefill's last-position logits (the SAME sampling
  rule the decode step uses, ``build_sample_fn`` — keeping serving
  outputs bitwise equal to solo ``generate()`` runs under greedy
  decoding), insert the prefilled single-lane cache into the slot's lane
  (``dynamic_update_slice`` over the traced slot index; cache donated),
  and write the slot's state entries in-program — so the host scheduler
  never synchronizes inside the admission path.

Per-step semantics mirror ``make_generate_fn``'s decode loop exactly
(write K/V at ``pos``, sample from the new logits, emit ``eos`` once done,
advance ``pos``) — that is what makes the scheduler-correctness contract
("every request's tokens == its solo generate() run") hold bitwise.
"""

import jax
import jax.numpy as jnp

from deepspeed_tpu.tools.lint.hotpath import hot_path

# the slot-state pytree: every leaf is a [num_slots] vector, every one a
# traced argument of the decode step (and donated through it)
SLOT_STATE_KEYS = ("token", "pos", "active", "remaining", "eos")


def init_slot_state(num_slots):
    """Host-side slot state: all lanes free.  ``eos=-1`` never matches a
    sampled token (ids are >= 0), so free lanes emit -1 and retire nothing."""
    import numpy as np
    return {
        "token": np.zeros((num_slots,), np.int32),
        "pos": np.zeros((num_slots,), np.int32),
        "active": np.zeros((num_slots,), bool),
        "remaining": np.zeros((num_slots,), np.int32),
        "eos": np.full((num_slots,), -1, np.int32),
    }


def make_decode_block_fn(module, sample_fn, param_transform, block,
                         cache_len):
    """The single reusable decode-step program:
    ``fn(params, cache, state, rng) -> (tokens [block, N], cache, state)``
    with the cache and slot state donated (argnums 1, 2).

    Each of the ``block`` in-program steps writes every slot's pending
    token at its own ``pos`` (per-row scatter write + per-row length
    mask), samples the next token, emits the slot's ``eos`` for lanes that
    already finished, and flips ``active`` off when a lane emits its eos
    or exhausts ``remaining`` — identical math to ``make_generate_fn``'s
    loop body, so greedy serving tokens match solo ``generate()`` bitwise.
    Retired/free lanes keep decoding as masked no-ops for at most
    ``block - 1`` steps until the host scheduler reclaims them; their
    writes land at a clamped ``pos`` and are overwritten by the next
    occupant before any of its queries can attend to them.
    """
    deq = param_transform if param_transform is not None else (lambda p: p)

    @hot_path("serving.decode_step")
    def decode_block(params, cache, state, rng):
        eos = state["eos"]

        def step(carry, _):
            cache, tok, pos, active, remaining, rng = carry
            logits, cache = module.apply(deq(params), tok[:, None], cache,
                                         pos, method=type(module).decode)
            rng, sub = jax.random.split(rng)
            nxt = sample_fn(logits[:, -1], sub).astype(jnp.int32)
            nxt = jnp.where(active, nxt, eos)
            done_now = active & ((nxt == eos) | (remaining <= 1))
            active = active & jnp.logical_not(done_now)
            # clamp: identity for live lanes (submit() bounds
            # prompt+max_new by cache_len); keeps dead lanes' masked
            # no-op writes inside the buffer forever
            pos = jnp.minimum(pos + 1, cache_len - 1)
            remaining = jnp.maximum(remaining - 1, 0)
            return (cache, nxt, pos, active, remaining, rng), nxt

        (cache, tok, pos, active, remaining, _), toks = jax.lax.scan(
            step, (cache, state["token"], state["pos"], state["active"],
                   state["remaining"], rng), None, length=block)
        new_state = {"token": tok, "pos": pos, "active": active,
                     "remaining": remaining, "eos": eos}
        return toks, cache, new_state

    return jax.jit(decode_block, donate_argnums=(1, 2))


def make_admit_fn(sample_fn):
    """The fused admission program:
    ``fn(cache, state, lane, logits, rng, slot, pos0, max_new, eos)
    -> (cache, state, first_token)`` with the cache and slot state
    donated (argnums 0, 1).

    One dispatch does everything an admission needs ON DEVICE: sample the
    first token from the prefill's last-position logits (same fp32 rule
    as the decode step — ``build_sample_fn`` — so greedy admission tokens
    match solo runs bitwise), write the [L, 1, S, ...] prefilled lane into
    slot ``slot`` of the big cache (``dynamic_update_slice`` over the
    traced slot index), and flip the slot's state entries live — inactive
    when the request already finished at admission (first token == eos,
    or ``max_new == 1``).  Because the state write happens in-program,
    the host scheduler never has to synchronize on the first token before
    the next decode block can be dispatched: it reads ``first_token``
    lazily, one block behind (see ``ServingEngine``)."""

    @hot_path("serving.admit")
    def admit(cache, state, lane, logits, rng, slot, pos0, max_new, eos):
        first = sample_fn(logits[:, 0], rng).astype(jnp.int32)[0]

        def ins(buf, lbuf):
            return jax.lax.dynamic_update_slice(
                buf, lbuf.astype(buf.dtype), (0, slot, 0, 0))

        cache = {k: ins(cache[k], lane[k]) for k in cache}
        # finished-at-admission: eos on the first token (eos=-1 never
        # matches: sampled ids are >= 0), or a 1-token request
        active0 = (max_new > 1) & jnp.logical_not(first == eos)
        upd = lambda arr, val: arr.at[slot].set(val)
        state = {"token": upd(state["token"], first),
                 "pos": upd(state["pos"], pos0),
                 "active": upd(state["active"], active0),
                 "remaining": upd(state["remaining"],
                                  jnp.maximum(max_new - 1, 0)),
                 "eos": upd(state["eos"], eos)}
        return cache, state, first

    return jax.jit(admit, donate_argnums=(0, 1))


# --------------------------------------------------------------------- #
# Paged variants (docs/serving.md "Paged KV cache"): the KV workspace is
# a page POOL [L, num_pages, page_size, KVH*D] shared by all slots, and
# the per-slot page tables ([num_slots, pages_per_slot] int32) arrive as
# a TRACED argument on every dispatch — the host allocates/frees/shares
# pages, the programs' shapes never change.  Prefill writes land in the
# pool directly (make_paged_chunk_fn), so the paged admit has no lane to
# insert: it only samples the first token and flips the slot state.
# --------------------------------------------------------------------- #

def make_paged_decode_block_fn(module, sample_fn, param_transform, block,
                               cache_len):
    """The paged decode step:
    ``fn(params, cache, state, pages, rng) -> (tokens, cache, state)``
    with the page POOL and the slot state donated (argnums 1, 2) and the
    page table a plain traced input (tiny; rebuilt host-side per
    dispatch).  ``cache_len`` is the VIRTUAL lane length
    (pages_per_slot * page_size) — the dead-lane position clamp bound.
    Per-step math is identical to :func:`make_decode_block_fn`; only the
    cache write/read routes through the page table (see
    ``models/transformer.py`` ``_paged_write``/``_paged_gather``), so
    greedy paged serving stays bitwise equal to solo ``generate()``."""
    deq = param_transform if param_transform is not None else (lambda p: p)

    @hot_path("serving.decode_step_paged")
    def decode_block(params, cache, state, pages, rng):
        eos = state["eos"]

        def step(carry, _):
            cache, tok, pos, active, remaining, rng = carry
            # inactive lanes decode as masked no-ops but still WRITE a
            # k/v row each step — point their whole table row at the
            # trash page so the write can never land in pages the host
            # already handed to a newer occupant.  (The monolithic path
            # tolerates those writes because the next admit re-inserts
            # the whole lane; paged prefill writes the pool directly
            # BEFORE the admit flips `active`, so an unmasked free-lane
            # write here would corrupt a freshly prefilled prompt.)
            safe_pages = jnp.where(active[:, None], pages, 0)
            logits, cache = module.apply(
                deq(params), tok[:, None],
                {**cache, "pages": safe_pages},
                pos, method=type(module).decode)
            rng, sub = jax.random.split(rng)
            nxt = sample_fn(logits[:, -1], sub).astype(jnp.int32)
            nxt = jnp.where(active, nxt, eos)
            done_now = active & ((nxt == eos) | (remaining <= 1))
            active = active & jnp.logical_not(done_now)
            # dead lanes clamp to the last virtual position — its table
            # entry is the trash page once the host processed retirement
            pos = jnp.minimum(pos + 1, cache_len - 1)
            remaining = jnp.maximum(remaining - 1, 0)
            return (cache, nxt, pos, active, remaining, rng), nxt

        (cache, tok, pos, active, remaining, _), toks = jax.lax.scan(
            step, (cache, state["token"], state["pos"], state["active"],
                   state["remaining"], rng), None, length=block)
        new_state = {"token": tok, "pos": pos, "active": active,
                     "remaining": remaining, "eos": eos}
        return toks, cache, new_state

    return jax.jit(decode_block, donate_argnums=(1, 2))


def make_paged_chunk_fn(module, param_transform):
    """The paged admission-prefill chunk program:
    ``fn(params, cache, pages, chunk_ids, start, logits_at)`` — same
    body as the engine's per-chunk program but writing straight into the
    slot's pool pages through its ``[1, pages_per_slot]`` table row (no
    single-lane staging cache, no admit-time insert).  The POOL is
    donated (argnum 1); the table row is a separate traced input so the
    donation aliases cleanly."""
    deq = param_transform if param_transform is not None else (lambda p: p)

    @hot_path("serving.prefill_chunk_paged")
    def chunk_step(params, cache, pages, chunk_ids, start, logits_at):
        return module.apply(deq(params), chunk_ids,
                            {**cache, "pages": pages}, start,
                            method=type(module).decode,
                            logits_at=logits_at)

    return jax.jit(chunk_step, donate_argnums=(1,))


def make_paged_admit_fn(sample_fn):
    """The paged admission program:
    ``fn(state, logits, rng, slot, pos0, max_new, eos) -> (state,
    first_token)`` with the slot state donated (argnum 0).  The prefill
    already wrote the prompt's K/V into the slot's pages, so admission
    is just the first-token sample (same ``build_sample_fn`` rule — the
    bitwise contract) plus the in-program slot-state write."""

    @hot_path("serving.admit_paged")
    def admit(state, logits, rng, slot, pos0, max_new, eos):
        first = sample_fn(logits[:, 0], rng).astype(jnp.int32)[0]
        active0 = (max_new > 1) & jnp.logical_not(first == eos)
        upd = lambda arr, val: arr.at[slot].set(val)
        state = {"token": upd(state["token"], first),
                 "pos": upd(state["pos"], pos0),
                 "active": upd(state["active"], active0),
                 "remaining": upd(state["remaining"],
                                  jnp.maximum(max_new - 1, 0)),
                 "eos": upd(state["eos"], eos)}
        return state, first

    return jax.jit(admit, donate_argnums=(0,))
