"""Compression-aware training (reference ``deepspeed/compression/``):
QAT weight/activation quantization, sparse/row/head/channel pruning,
layer-reduction distillation — as pure transforms over flax param pytrees."""

from .compress import (CompressionSpec, apply_compression, init_compression,
                       quant_act, redundancy_clean, student_initialization)
from .config import get_compression_config, get_layer_reduction_config
from .scheduler import compression_scheduler
from . import constants
