"""Native-op + ZeRO-Offload tests.

Analog of reference ``tests/unit/ops/adam/test_cpu_adam.py`` (golden-value
comparison of the C++ kernel vs a reference implementation),
``tests/unit/ops/aio/test_aio.py`` (async read/write roundtrips), and the
offload cases of ``tests/unit/runtime/zero/test_zero.py`` (train with
offload_optimizer on cpu/nvme, checkpoint roundtrip).
"""

import numpy as np
import pytest

import jax

import deepspeed_tpu
from simple_model import SimpleModel, random_batch


# ------------------------------------------------------------------ #
# C++ cpu_adam vs reference math
# ------------------------------------------------------------------ #
def _torch_style_adamw(p, g, m, v, lr, b1, b2, eps, wd, step):
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    mhat = m / (1 - b1 ** step)
    vhat = v / (1 - b2 ** step)
    p = p * (1 - lr * wd) - lr * mhat / (np.sqrt(vhat) + eps)
    return p, m, v


def test_cpu_adam_matches_reference():
    from deepspeed_tpu.ops.adam import cpu_adam
    rng = np.random.default_rng(1)
    n = 4097
    p = rng.standard_normal(n).astype(np.float32)
    g = rng.standard_normal(n).astype(np.float32)
    m = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    pr, mr, vr = p.copy(), m.copy(), v.copy()
    for step in (1, 2, 3):
        cpu_adam.adam_step(p, m, v, g, 1e-3, 0.9, 0.999, 1e-8, 0.01,
                           True, True, step)
        pr, mr, vr = _torch_style_adamw(pr, g, mr, vr, 1e-3, 0.9, 0.999,
                                        1e-8, 0.01, step)
    # eps placement differs (sqrt(vhat)+eps vs sqrt(v)/sqrt(bc2)+eps): allow
    # small tolerance — identical to the reference kernel's own convention
    np.testing.assert_allclose(p, pr, rtol=2e-4, atol=2e-6)


def test_cpu_adam_bf16_out():
    import ml_dtypes
    from deepspeed_tpu.ops.adam import cpu_adam
    rng = np.random.default_rng(2)
    n = 1025
    p = rng.standard_normal(n).astype(np.float32)
    g = rng.standard_normal(n).astype(np.float32)
    m = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    bf = np.zeros(n, np.uint16)
    cpu_adam.adam_step(p, m, v, g, 1e-2, 0.9, 0.999, 1e-8, 0.0, True, True, 1,
                       bf16_out=bf)
    ref = p.astype(ml_dtypes.bfloat16)
    assert np.array_equal(ref.view(np.uint16), bf)


def test_cpu_adagrad():
    from deepspeed_tpu.ops.adam import cpu_adam
    rng = np.random.default_rng(3)
    n = 513
    p = rng.standard_normal(n).astype(np.float32)
    g = rng.standard_normal(n).astype(np.float32)
    v = np.zeros(n, np.float32)
    pr, vr = p.copy(), v.copy()
    cpu_adam.adagrad_step(p, v, g, 1e-2, 1e-10, 0.0)
    vr = vr + g * g
    pr = pr - 1e-2 * g / (np.sqrt(vr) + 1e-10)
    np.testing.assert_allclose(p, pr, rtol=1e-5)


# ------------------------------------------------------------------ #
# aio + swapper
# ------------------------------------------------------------------ #
def test_aio_roundtrip(tmp_path):
    from deepspeed_tpu.ops import aio
    if not aio.is_available():
        pytest.skip(f"aio lib unavailable: {aio.build_error()}")
    h = aio.AsyncIOHandle(block_size=1 << 16, thread_count=2)
    buf = np.random.default_rng(0).standard_normal(100_000).astype(np.float32)
    path = str(tmp_path / "t.bin")
    h.async_pwrite(buf, path)
    h.wait()
    rd = np.empty_like(buf)
    h.async_pread(rd, path)
    h.wait()
    assert np.array_equal(buf, rd)


def test_async_tensor_swapper(tmp_path):
    from deepspeed_tpu.runtime.swap_tensor import AsyncTensorSwapper
    sw = AsyncTensorSwapper(str(tmp_path), buffer_count=2, thread_count=2)
    a = np.arange(1000, dtype=np.float32)
    b = np.arange(2000, dtype=np.float32) * 2
    sw.swap_out("a", a)
    sw.swap_out("b", b)
    sw.synchronize_writes()
    assert np.array_equal(sw.swap_in("a", 1000), a)
    assert np.array_equal(sw.swap_in("b", 2000), b)


def test_optimizer_swapper(tmp_path):
    from deepspeed_tpu.runtime.swap_tensor import OptimizerSwapper
    sw = OptimizerSwapper(str(tmp_path), pipeline_write=True)
    n = 777
    m = np.random.default_rng(0).standard_normal(n).astype(np.float32)
    a = np.ones(n, np.float32)
    v = np.full(n, 2.0, np.float32)
    sw.register("w", n, m, a, v)
    mo, ao, vo = (np.empty(n, np.float32) for _ in range(3))
    sw.swap_in("w", mo, ao, vo)
    assert np.array_equal(mo, m) and np.array_equal(ao, a) and np.array_equal(vo, v)
    m2 = m * 3
    sw.swap_out("w", m2, a, v)
    sw.drain()
    sw.swap_in("w", mo, ao, vo)
    assert np.array_equal(mo, m2)


# ------------------------------------------------------------------ #
# Engine with offloaded optimizer
# ------------------------------------------------------------------ #
def _offload_config(device, nvme_path=None):
    off = {"device": device}
    if nvme_path:
        off["nvme_path"] = nvme_path
    return {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2, "offload_optimizer": off},
    }


def _train(engine, steps, seed=0):
    # fixed batch, as in test_engine: memorization makes the loss-decrease
    # assertion deterministic
    losses = []
    for i in range(steps):
        batch = random_batch(batch_size=16, seed=seed)
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return losses


def test_offload_cpu_trains():
    engine, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=_offload_config("cpu"))
    losses = _train(engine, 8)
    assert losses[-1] < losses[0], losses
    # device params stayed in compute dtype (the HBM saving)
    import jax.numpy as jnp
    leaf = jax.tree.leaves(engine.params)[0]
    assert leaf.dtype == jnp.bfloat16


def test_offload_nvme_trains(tmp_path):
    engine, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16),
        config=_offload_config("nvme", str(tmp_path)))
    losses = _train(engine, 6)
    assert losses[-1] < losses[0], losses
    files = list(tmp_path.iterdir())
    assert files, "no swap files written to nvme path"


def test_offload_nvme_pipelined(tmp_path):
    cfg = _offload_config("nvme", str(tmp_path))
    cfg["zero_optimization"]["offload_optimizer"].update(
        pipeline_read=True, pipeline_write=True)
    engine, *_ = deepspeed_tpu.initialize(model=SimpleModel(hidden_dim=16),
                                          config=cfg)
    losses = _train(engine, 6)
    assert losses[-1] < losses[0], losses
    # pipelined trajectory == sequential trajectory
    from deepspeed_tpu.parallel import topology
    topology.reset_topology()
    import tempfile
    with tempfile.TemporaryDirectory() as d2:
        e2, *_ = deepspeed_tpu.initialize(model=SimpleModel(hidden_dim=16),
                                          config=_offload_config("nvme", d2))
        losses2 = _train(e2, 6)
    np.testing.assert_allclose(losses, losses2, rtol=1e-5)


def test_offload_matches_device_adamw():
    """Host C++ AdamW and the jitted device AdamW walk the same trajectory."""
    cfg_dev = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 0},
    }
    e_dev, *_ = deepspeed_tpu.initialize(model=SimpleModel(hidden_dim=16),
                                         config=cfg_dev)
    _train(e_dev, 4, seed=7)
    from deepspeed_tpu.parallel import topology
    topology.reset_topology()
    e_off, *_ = deepspeed_tpu.initialize(model=SimpleModel(hidden_dim=16),
                                         config=_offload_config("cpu"))
    _train(e_off, 4, seed=7)
    ref = jax.tree.leaves(jax.device_get(e_dev.params))
    got = e_off._host_opt.master_params_tree()
    got = [g.reshape(r.shape) for g, r in zip(jax.tree.leaves(got), ref)]
    # trajectories diverge slightly: offload fwd runs in bf16
    for r, g in zip(ref, got):
        np.testing.assert_allclose(r, g, rtol=0.1, atol=0.05)


def test_offload_checkpoint_roundtrip(tmp_path):
    engine, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=_offload_config("cpu"))
    _train(engine, 3)
    engine.save_checkpoint(str(tmp_path / "ckpt"))
    step_before = engine._host_opt.step_count
    masters_before = [m.copy() for m in engine._host_opt.masters]
    _train(engine, 2)
    engine.load_checkpoint(str(tmp_path / "ckpt"))
    assert engine._host_opt.step_count == step_before
    for a, b in zip(engine._host_opt.masters, masters_before):
        np.testing.assert_array_equal(a, b)


def test_offload_load_without_opt_states_reseeds_masters(tmp_path):
    """Loading a checkpoint without host optimizer states must re-seed the
    fp32 masters from the loaded params — otherwise the next step() runs
    Adam over stale masters and silently reverts the model."""
    engine, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=_offload_config("cpu"))
    _train(engine, 3)
    engine.save_checkpoint(str(tmp_path / "ckpt"))
    trained = [np.asarray(jax.device_get(l), np.float32).ravel()
               for l in jax.tree.leaves(engine.params)]
    _train(engine, 2)
    engine.load_checkpoint(str(tmp_path / "ckpt"), load_optimizer_states=False)
    for m, p in zip(engine._host_opt.masters, trained):
        np.testing.assert_allclose(m, p, rtol=1e-2, atol=1e-2)  # bf16 params
    # and one more step keeps training near the loaded point, not init
    loss = _train(engine, 1)
    assert np.isfinite(loss[-1])


@pytest.mark.parametrize("device", ["cpu", "nvme"])
def test_offload_load_params_reseeds_host_masters(device, tmp_path):
    """GatheredParameters surgery + load_params under ZeRO-Offload: the host
    fp32 masters are authoritative, so load_params must re-seed them (values
    only — moments survive) or the next step silently reverts the surgery."""
    engine, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16),
        config=_offload_config(device,
                               str(tmp_path) if device == "nvme" else None))
    _train(engine, 2)
    with deepspeed_tpu.zero.GatheredParameters(engine.params) as g:
        name = sorted(g.full["params"])[0]
        g.full["params"][name]["kernel"][:] = 0.125
    engine.load_params(g.params)
    # one more step: updates start FROM the surgically-set weights
    _train(engine, 1, seed=50)
    got = np.asarray(jax.device_get(
        engine.params["params"][name]["kernel"])).astype(np.float32)
    # adam with lr 1e-2 moves weights by ~lr per step; surgery must persist
    # (without re-seeding, values revert to the pre-surgery trajectory ~0)
    assert np.all(np.abs(got - 0.125) < 0.05), got


def test_offload_fresh_engine_load_restores_moments(tmp_path):
    """Checkpoint with offloaded optimizer loaded into a FRESH engine:
    saved host Adam moments must be restored, not re-zeroed."""
    ck = tmp_path / "ck"
    engine, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=_offload_config("cpu"))
    _train(engine, 4)
    engine.save_checkpoint(str(ck))
    want_m = [m.copy() for m in engine._host_opt.cpu_opt.exp_avg]
    want_step = engine._host_opt.step_count

    fresh, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=_offload_config("cpu"))
    fresh.load_checkpoint(str(ck))
    got_m = fresh._host_opt.cpu_opt.exp_avg
    assert fresh._host_opt.step_count == want_step
    assert any(np.abs(m).max() > 0 for m in got_m), "moments zeroed"
    for a, b in zip(want_m, got_m):
        np.testing.assert_allclose(a, b)
