"""TL005 positive fixture: per-step config lookups on a hot path."""
from deepspeed_tpu.tools.lint.hotpath import hot_path


@hot_path("fixture.train_step")
def train_step(params, batch, config):
    lr = config["lr"]                        # TL005
    clip = config.get("gradient_clipping")   # TL005
    return params, lr, clip
