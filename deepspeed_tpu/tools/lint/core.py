"""tpu-lint core: source model, rule registry, suppressions, call graph.

The linter is purely static — ``ast`` over the package sources, no imports
of the code under analysis.  Rules live in ``rules/`` (one module per rule)
and register themselves with :func:`rule`; each receives a
:class:`ModuleInfo` and yields :class:`Finding`s.  Suppression is per line::

    x = loss.item()   # tpu-lint: disable=TL001 -- logged once per epoch

and a suppression on a ``def`` line covers the whole function body.
"""

import ast
import dataclasses
import os
import re
from typing import Iterator, List, Optional

_SUPPRESS_RE = re.compile(
    r"#\s*tpu-lint:\s*disable=([A-Z0-9*, ]+)(?:\s*--\s*(.*))?")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def __str__(self):
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


@dataclasses.dataclass
class FunctionInfo:
    node: ast.AST                 # FunctionDef / AsyncFunctionDef / Lambda
    qualname: str
    name: str
    class_name: Optional[str]
    hot: bool                     # carries @hot_path or is nested in one
    hot_name: Optional[str] = None

    @property
    def params(self):
        a = self.node.args
        names = [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]
        return [n for n in names if n not in ("self", "cls")]


class ModuleInfo:
    """One parsed source file: tree, functions, suppressions, call graph."""

    def __init__(self, path, text):
        self.path = path
        self.text = text
        self.tree = ast.parse(text, filename=path)
        self.lines = text.splitlines()
        self._suppressions = self._parse_suppressions()
        self.functions: List[FunctionInfo] = []
        self._collect_functions()
        self._mark_hot_reachable()

    # ---------------------------------------------------------------- #
    # suppressions
    # ---------------------------------------------------------------- #
    def _parse_suppressions(self):
        out = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
        return out

    def suppressed(self, line, rule_id):
        rules = self._suppressions.get(line)
        if rules and (rule_id in rules or "*" in rules):
            return True
        # a suppression on the def line (or a decorator line) covers the
        # whole function body
        for fn in self.functions:
            node = fn.node
            if not hasattr(node, "end_lineno"):
                continue
            decos = getattr(node, "decorator_list", [])
            start = min([node.lineno] + [d.lineno for d in decos])
            if start <= line <= (node.end_lineno or node.lineno):
                for header_line in range(start, node.body[0].lineno
                                         if node.body else node.lineno):
                    rules = self._suppressions.get(header_line)
                    if rules and (rule_id in rules or "*" in rules):
                        return True
        return False

    def suppression_count(self, rule_id):
        return sum(1 for rules in self._suppressions.values()
                   if rule_id in rules or "*" in rules)

    # ---------------------------------------------------------------- #
    # function collection + hot-path propagation
    # ---------------------------------------------------------------- #
    def _collect_functions(self):
        module = self

        class V(ast.NodeVisitor):
            def __init__(self):
                self.stack = []       # (kind, name) — 'class' or 'func'

            def _qual(self, name):
                return ".".join([n for _, n in self.stack] + [name])

            def _class(self):
                for kind, name in reversed(self.stack):
                    if kind == "class":
                        return name
                return None

            def visit_ClassDef(self, node):
                self.stack.append(("class", node.name))
                self.generic_visit(node)
                self.stack.pop()

            def _visit_func(self, node):
                hot_name = _hot_path_decorator_name(node)
                module.functions.append(FunctionInfo(
                    node=node, qualname=self._qual(node.name),
                    name=node.name, class_name=self._class(),
                    hot=hot_name is not None, hot_name=hot_name))
                self.stack.append(("func", node.name))
                self.generic_visit(node)
                self.stack.pop()

            visit_FunctionDef = _visit_func
            visit_AsyncFunctionDef = _visit_func

        V().visit(self.tree)

    def _mark_hot_reachable(self):
        """Hotness propagates (a) to functions lexically nested inside a hot
        function and (b) along same-module calls, resolved by bare name
        (``f(...)``, ``self.f(...)``, ``obj.f(...)`` all resolve to any
        function/method named ``f`` in this module — deliberately
        over-approximate: a lint prefers a suppressible false positive to a
        silent host sync)."""
        by_name = {}
        for fn in self.functions:
            by_name.setdefault(fn.name, []).append(fn)

        changed = True
        while changed:
            changed = False
            for fn in self.functions:
                if not fn.hot:
                    continue
                # (a) nested defs
                for child in ast.walk(fn.node):
                    if child is fn.node:
                        continue
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        for other in self.functions:
                            if other.node is child and not other.hot:
                                other.hot = True
                                other.hot_name = fn.hot_name
                                changed = True
                # (b) called names
                for callee in _called_names(fn.node):
                    for other in by_name.get(callee, []):
                        if not other.hot:
                            other.hot = True
                            other.hot_name = fn.hot_name
                            changed = True

    def hot_functions(self):
        return [f for f in self.functions if f.hot]

    def enclosing_function(self, node):
        """Innermost FunctionInfo whose span contains ``node``."""
        best = None
        for fn in self.functions:
            n = fn.node
            if n.lineno <= node.lineno <= (n.end_lineno or n.lineno):
                if best is None or n.lineno >= best.node.lineno:
                    best = fn
        return best


def _hot_path_decorator_name(node):
    for dec in getattr(node, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted_name(target)
        if name and name.split(".")[-1] == "hot_path":
            if isinstance(dec, ast.Call) and dec.args and \
                    isinstance(dec.args[0], ast.Constant):
                return str(dec.args[0].value)
            return node.name
    return None


def _called_names(fn_node):
    """Bare names of everything called inside ``fn_node`` (excluding calls
    inside nested defs — those propagate through containment instead)."""
    out = set()
    nested = set()
    for child in ast.walk(fn_node):
        if child is not fn_node and isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(child):
                nested.add(sub)
    for child in ast.walk(fn_node):
        if child in nested or not isinstance(child, ast.Call):
            continue
        f = child.func
        if isinstance(f, ast.Name):
            out.add(f.id)
        elif isinstance(f, ast.Attribute):
            out.add(f.attr)
    return out


def dotted_name(node):
    """'jax.jit' for Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# -------------------------------------------------------------------- #
# rule registry
# -------------------------------------------------------------------- #
RULES = {}


def rule(rule_id, title):
    """Register ``check(module: ModuleInfo) -> Iterator[Finding]``."""
    def register(check):
        check.rule_id = rule_id
        check.title = title
        RULES[rule_id] = check
        return check
    return register


def iter_python_files(paths):
    for path in paths:
        if os.path.isfile(path) and path.endswith(".py"):
            yield path
        elif os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def run_lint(paths, rules=None):
    """Lint ``paths``; returns (findings, stats).

    ``stats``: {"files": n, "suppressed": {rule_id: count}}.
    """
    from deepspeed_tpu.tools.lint import rules as _rules  # noqa: F401 — registers
    selected = {k: v for k, v in RULES.items()
                if rules is None or k in rules}
    findings, stats = [], {"files": 0, "suppressed": {}}
    for path in iter_python_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                module = ModuleInfo(path, fh.read())
        except SyntaxError as e:
            findings.append(Finding("TL000", path, e.lineno or 1, 0,
                                    f"syntax error: {e.msg}"))
            continue
        stats["files"] += 1
        for rule_id, check in sorted(selected.items()):
            for f in check(module):
                if module.suppressed(f.line, rule_id):
                    stats["suppressed"][rule_id] = \
                        stats["suppressed"].get(rule_id, 0) + 1
                else:
                    findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, stats
