"""Universal checkpoint: fold a training checkpoint into per-parameter fp32
files loadable at ANY parallel topology.

Reference parity: ``deepspeed/checkpoint/universal_checkpoint.py:12``
(``load_hp_checkpoint_state``) + the ``ds_to_universal`` offline conversion
flow.  The reference reconstructs each parameter's full fp32 value and
optimizer moments from ZeRO fragments scattered over DP ranks
(``utils/tensor_fragment.py``); here the checkpoint store is already
logically global, so conversion is a cast-and-split into one directory per
parameter:

    <out_dir>/
      zero/<dotted.param.path>/fp32.npy
      zero/<dotted.param.path>/<moment>.npy      (adam mu/nu, ...)
      universal_meta.pkl

Loading pushes each parameter through the live engine's sharding plan —
resharding to the new mesh happens in ``jax.device_put``.
"""

import os
import pickle

import numpy as np

import jax

from deepspeed_tpu.checkpoint.deepspeed_checkpoint import (
    DeepSpeedCheckpoint, ZeROCheckpoint, _flatten_with_paths)
from deepspeed_tpu.utils.logging import logger

UNIVERSAL_META = "universal_meta.pkl"
ZERO_SUBDIR = "zero"
FP32_NAME = "fp32.npy"


def _param_dir(out_dir, name):
    return os.path.join(out_dir, ZERO_SUBDIR, name)


def convert_to_universal(ckpt_dir, out_dir, tag=None):
    """Offline conversion: engine checkpoint → universal layout."""
    ckpt = ZeROCheckpoint(ckpt_dir, tag=tag)
    flat_params = ckpt.flat_parameters()
    moments = ckpt.flat_optimizer_moments()
    os.makedirs(out_dir, exist_ok=True)
    for name, value in flat_params.items():
        pdir = _param_dir(out_dir, name)
        os.makedirs(pdir, exist_ok=True)
        v = value.astype(np.float32) if np.issubdtype(value.dtype, np.floating) \
            else value
        np.save(os.path.join(pdir, FP32_NAME), v)
        for field, per_param in moments.items():
            if name in per_param:
                m = per_param[name]
                m = m.astype(np.float32) if np.issubdtype(m.dtype, np.floating) else m
                np.save(os.path.join(pdir, f"{field}.npy"), m)
    meta = dict(ckpt.meta)
    meta["universal_source_tag"] = ckpt.tag
    meta["param_names"] = sorted(flat_params.keys())
    meta["moment_fields"] = sorted(moments.keys())
    with open(os.path.join(out_dir, UNIVERSAL_META), "wb") as f:
        pickle.dump(meta, f)
    logger.info(f"universal checkpoint: {len(flat_params)} params → {out_dir}")
    return out_dir


def load_universal_meta(universal_dir):
    with open(os.path.join(universal_dir, UNIVERSAL_META), "rb") as f:
        return pickle.load(f)


def load_hp_checkpoint_state(universal_dir, param_name):
    """Per-parameter high-precision state (reference
    ``universal_checkpoint.py:12``): {'fp32': arr, '<moment>': arr, ...}."""
    pdir = _param_dir(universal_dir, param_name)
    if not os.path.isdir(pdir):
        raise KeyError(f"no universal state for parameter {param_name!r}")
    out = {}
    for fname in os.listdir(pdir):
        if fname.endswith(".npy"):
            out[fname[:-4]] = np.load(os.path.join(pdir, fname))
    return out


def load_universal_into_engine(engine, universal_dir, load_optimizer_states=True):
    """Restore a universal checkpoint into a LIVE engine at whatever topology
    it runs — the analog of the reference's ``load_universal_checkpoint``
    path (``engine.py:772``)."""
    meta = load_universal_meta(universal_dir)
    if engine._params is None:
        raise RuntimeError("engine parameters not initialised yet; run one "
                           "forward (or init) before universal load")

    from deepspeed_tpu.runtime.zero.partition import path_to_str
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    flat_specs = {path_to_str(p): s for p, s in
                  jax.tree_util.tree_flatten_with_path(
                      engine._plan.param_specs,
                      is_leaf=lambda x: isinstance(x, P))[0]}

    def restore_param(path, current):
        name = path_to_str(path)
        try:
            state = load_hp_checkpoint_state(universal_dir, name)
        except KeyError:
            logger.warning(f"universal load: {name} missing, keeping current")
            return current
        arr = np.asarray(state["fp32"]).astype(current.dtype)
        if arr.shape != current.shape:
            raise ValueError(f"universal load: {name} shape {arr.shape} != "
                             f"live {current.shape}")
        sharding = NamedSharding(engine.mesh, flat_specs.get(name, P()))
        return jax.device_put(arr, sharding)

    engine._params = jax.tree_util.tree_map_with_path(restore_param, engine._params)

    if load_optimizer_states and engine._opt_state is not None \
            and meta.get("moment_fields"):
        params_def = jax.tree.structure(engine._params)

        def restore_moment_tree(field, field_name):
            def one(path, current):
                name = path_to_str(path)
                try:
                    state = load_hp_checkpoint_state(universal_dir, name)
                except KeyError:
                    return current
                if field_name not in state:
                    return current
                arr = np.asarray(state[field_name]).astype(current.dtype)
                return jax.device_put(arr, current.sharding)
            return jax.tree_util.tree_map_with_path(one, field)

        def visit(field, name):
            try:
                if jax.tree.structure(field) == params_def:
                    return restore_moment_tree(field, name)
            except Exception:
                pass
            if hasattr(field, "_fields"):
                return type(field)(*[visit(getattr(field, f),
                                           f"{name}.{f}" if name else f)
                                     for f in field._fields])
            if isinstance(field, tuple):
                return tuple(visit(f, f"{name}.{i}" if name else str(i))
                             for i, f in enumerate(field))
            if isinstance(field, list):
                return [visit(f, f"{name}.{i}" if name else str(i))
                        for i, f in enumerate(field)]
            if isinstance(field, dict):
                return {k: visit(f, f"{name}.{k}" if name else str(k))
                        for k, f in field.items()}
            return field

        engine._opt_state = visit(engine._opt_state, "")

    engine.global_steps = meta.get("global_steps", 0)
    engine.global_samples = meta.get("global_samples", 0)
    engine.micro_steps = meta.get("micro_steps", 0)
    engine.skipped_steps = meta.get("skipped_steps", 0)
    if engine.lr_scheduler is not None and meta.get("lr_scheduler"):
        engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])
    logger.info(f"universal checkpoint loaded from {universal_dir} at "
                f"topology {dict(engine.mesh.shape)}")
    return engine
