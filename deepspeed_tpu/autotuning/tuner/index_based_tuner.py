"""Grid / random tuners (reference ``tuner/index_based_tuner.py``)."""

import random

from deepspeed_tpu.autotuning.tuner.base_tuner import BaseTuner


class GridSearchTuner(BaseTuner):
    """Enumerate the space in order (reference GridSearchTuner)."""


class RandomTuner(BaseTuner):
    """Shuffled enumeration (reference RandomTuner)."""

    def __init__(self, exps, resource_manager, metric="throughput", seed=0):
        super().__init__(exps, resource_manager, metric)
        rng = random.Random(seed)
        rng.shuffle(self.all_exps)
