"""BF16_Optimizer — bf16 working weights over fp32 masters, no loss scaling.

Reference parity: ``runtime/bf16_optimizer.py:30`` (``BF16_Optimizer``): fp32
master params partitioned ZeRO-1-style over the DP group (``:87-165``), bf16
working copies, fp32 gradient accumulation, global-norm clipping, and a unit
loss scale (bf16's exponent range makes dynamic scaling unnecessary).

TPU redesign: in production the engine's fused train step IS this optimizer —
masters/opt-state carry ZeRO sharding annotations from
``runtime/zero/partition.py`` and XLA emits the reduce-scatter/all-gather.
This standalone class exists for reference-API users and tests: functional
state, one jitted update, optional master/opt-state sharding over the live
``dp`` mesh axis (the ZeRO-1 partitioning of the reference).
"""

import jax
import jax.numpy as jnp


class BF16_Optimizer:

    def __init__(self, init_optimizer, params=None, mpu=None, clip_grad=0.0,
                 norm_type=2, allgather_bucket_size=None, dp_process_group=None,
                 timers=None, shard_masters=True):
        if norm_type != 2:
            raise NotImplementedError("only L2 grad-norm clipping")
        self.optimizer = init_optimizer
        self.clip_grad = float(clip_grad or 0.0)
        self.shard_masters = shard_masters
        self.fp32_groups_flat = None
        self.opt_state = None
        self.step_count = 0
        self.overflow = False          # bf16 runs unit scale; kept for API
        self._accum_grads = None
        if params is not None:
            self.initialize_masters(params)

    # -------------------------------------------------------------- #
    def _master_shardings(self, masters):
        """ZeRO-1-style partitioning of masters/opt-state over the dp axis
        (reference ``bf16_optimizer.py:87-165``) — on TPU this is a sharding
        annotation, applied only when a multi-device topology is live."""
        from deepspeed_tpu.parallel.topology import get_topology
        topo = get_topology()
        if topo is None or not self.shard_masters:
            return None
        mesh = topo.mesh
        dp_axes = tuple(a for a in ("dp", "edp") if mesh.shape.get(a, 1) > 1)
        if not dp_axes:
            return None
        from deepspeed_tpu.runtime.zero.partition import (apply_zero_to_spec,
                                                          choose_zero_dim)
        from jax.sharding import NamedSharding, PartitionSpec as P

        def sh(leaf):
            spec = apply_zero_to_spec(leaf.shape, P(*([None] * leaf.ndim)),
                                      mesh, dp_axes)
            return NamedSharding(mesh, spec)
        return jax.tree.map(sh, masters)

    def initialize_masters(self, bf16_params):
        self.fp32_groups_flat = jax.tree.map(
            lambda p: jnp.asarray(p, jnp.float32), bf16_params)
        shardings = self._master_shardings(self.fp32_groups_flat)
        if shardings is not None:
            self.fp32_groups_flat = jax.tree.map(
                jax.device_put, self.fp32_groups_flat, shardings)
        self.opt_state = self.optimizer.init(self.fp32_groups_flat)

    @property
    def cur_scale(self):
        return 1.0

    def scale_loss(self, loss):
        return loss                    # unit scale

    def backward(self, grads):
        """Stage grads; repeated calls accumulate in fp32 (the reference
        accumulates bf16 grads into fp32 buffers across GAS boundaries)."""
        grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        if self._accum_grads is None:
            self._accum_grads = grads
        else:
            self._accum_grads = jax.tree.map(jnp.add, self._accum_grads, grads)

    # -------------------------------------------------------------- #
    def _step_fn(self):
        clip = self.clip_grad
        opt = self.optimizer

        def step(masters, opt_state, grads, step_no):
            flat = jax.tree.leaves(grads)
            gnorm = jnp.sqrt(sum(jnp.sum(g * g) for g in flat))
            if clip > 0:
                factor = jnp.minimum(1.0, clip / (gnorm + 1e-6))
                grads = jax.tree.map(lambda g: g * factor, grads)
            new_masters, new_opt = opt.update(grads, opt_state, masters,
                                              step=step_no)
            return new_masters, new_opt, gnorm

        return jax.jit(step, donate_argnums=(0, 1))

    def step(self, closure=None):
        assert self._accum_grads is not None, "backward() not called"
        assert self.fp32_groups_flat is not None, \
            "initialize_masters() not called"
        if not hasattr(self, "_jitted_step"):
            self._jitted_step = self._step_fn()
        self.step_count += 1
        (self.fp32_groups_flat, self.opt_state,
         self._last_norm) = self._jitted_step(
            self.fp32_groups_flat, self.opt_state, self._accum_grads,
            jnp.asarray(self.step_count, jnp.int32))
        self._accum_grads = None
        return False                   # never overflows (unit scale)

    # -------------------------------------------------------------- #
    def get_bf16_params(self):
        """Current working (bf16) weights derived from the masters — the
        all-gathered update the reference broadcasts back to the model."""
        return jax.tree.map(lambda p: p.astype(jnp.bfloat16),
                            self.fp32_groups_flat)

    def state_dict(self):
        return {
            "step": self.step_count,
            "fp32_groups_flat": jax.device_get(self.fp32_groups_flat),
            "optimizer_state": jax.device_get(self.opt_state),
        }

    def load_state_dict(self, sd, load_optimizer_states=True):
        self.step_count = sd["step"]
        self.fp32_groups_flat = jax.tree.map(jnp.asarray,
                                             sd["fp32_groups_flat"])
        shardings = self._master_shardings(self.fp32_groups_flat)
        if shardings is not None:   # restore the ZeRO-1 dp partitioning
            self.fp32_groups_flat = jax.tree.map(
                jax.device_put, self.fp32_groups_flat, shardings)
        if load_optimizer_states and sd.get("optimizer_state") is not None:
            from deepspeed_tpu.runtime.utils import rehydrate_opt_state
            self.opt_state = rehydrate_opt_state(self.opt_state,
                                                 sd["optimizer_state"])
