"""Public zero API tests (reference ``deepspeed.zero``): Init sharded-at-
birth materialization and GatheredParameters gather→surgery→re-shard."""

import numpy as np

import jax
import jax.numpy as jnp
import flax.linen as nn

import deepspeed_tpu
from deepspeed_tpu.parallel.topology import initialize_topology, reset_topology


class Net(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.Dense(64)(nn.relu(nn.Dense(128)(x)))


def test_zero_init_materializes_sharded():
    reset_topology()
    initialize_topology(dp=8)
    try:
        model = Net()
        with deepspeed_tpu.zero.Init(
                config={"zero_optimization": {"stage": 3}}) as zinit:
            assert deepspeed_tpu.zero.Init.is_active()
            params = zinit.materialize(model.init, jax.random.key(0),
                                       jnp.ones((2, 16)))
        assert not deepspeed_tpu.zero.Init.is_active()
        # stage 3: param leaves sharded over the dp axis where divisible
        leaves = jax.tree.leaves(params)
        assert any(not l.sharding.is_fully_replicated for l in leaves)
        assert zinit.plan is not None
        # forward works from the sharded tree
        out = jax.jit(model.apply)(params, jnp.ones((2, 16)))
        assert out.shape == (2, 64)
    finally:
        reset_topology()


def test_gathered_parameters_surgery_roundtrip():
    reset_topology()
    initialize_topology(dp=8)
    try:
        model = Net()
        with deepspeed_tpu.zero.Init(
                config={"zero_optimization": {"stage": 3}}) as zinit:
            params = zinit.materialize(model.init, jax.random.key(0),
                                       jnp.ones((2, 16)))
        with deepspeed_tpu.zero.GatheredParameters(params) as g:
            # full numpy view, in-place surgery (layer auto-names differ by
            # construction order — pick the first Dense)
            name = sorted(g.full["params"])[0]
            k = g.full["params"][name]["kernel"]
            assert isinstance(k, np.ndarray)
            k[:] = 0.25
        new = g.params
        k2 = new["params"][name]["kernel"]
        # sharding preserved, values updated
        assert k2.sharding == params["params"][name]["kernel"].sharding
        np.testing.assert_allclose(np.asarray(jax.device_get(k2)), 0.25)
        # disabled context is a zero-cost passthrough (live device tree,
        # read-only); surgery requires enabled=True
        with deepspeed_tpu.zero.GatheredParameters(params, enabled=False) as g2:
            assert g2.full is params
        assert g2.params is params
    finally:
        reset_topology()


def test_gathered_parameters_engine_writeback():
    """Full reference workflow: engine → gather → surgery → load_params →
    training continues with the modified weights."""
    reset_topology()
    try:
        from simple_model import SimpleModel, random_batch
        engine, *_ = deepspeed_tpu.initialize(
            model=SimpleModel(),
            config={"train_micro_batch_size_per_gpu": 8,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 3}})
        loss = engine(random_batch())
        engine.backward(loss)
        engine.step()

        with deepspeed_tpu.zero.GatheredParameters(engine.params) as g:
            name = sorted(g.full["params"])[0]
            g.full["params"][name]["kernel"][:] = 0.125
        engine.load_params(g.params)
        got = np.asarray(jax.device_get(
            engine.params["params"][name]["kernel"]))
        np.testing.assert_allclose(got, 0.125)
        # sharding preserved and training still runs
        assert engine.params["params"][name]["kernel"].sharding == \
            g.params["params"][name]["kernel"].sharding
        loss = engine(random_batch())
        engine.backward(loss)
        engine.step()

        # default zero.Init (no config) shards at birth (stage-3 contract)
        with deepspeed_tpu.zero.Init() as zi:
            p = zi.materialize(Net().init, jax.random.key(1),
                               jnp.ones((2, 16)))
        assert any(not l.sharding.is_fully_replicated
                   for l in jax.tree.leaves(p))
    finally:
        reset_topology()
