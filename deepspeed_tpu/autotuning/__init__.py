from deepspeed_tpu.autotuning.autotuner import Autotuner, autotune  # noqa: F401
from deepspeed_tpu.autotuning.scheduler import Experiment, ResourceManager  # noqa: F401
