"""Preemption-aware serving loop — the serving analog of
``runtime/fault/supervisor.run_resilient``.

:func:`serve_resilient` drives a :class:`ServingEngine` until everything
submitted has reached a terminal status, watching a
:class:`~deepspeed_tpu.elasticity.elastic_agent.DSElasticAgent` for
SIGTERM preemption: on preemption it stops admission, drains the
in-flight slots under the config's ``drain_budget_s``, snapshots the
undrained requests crash-atomically (``ServingEngine.preempt``) and
returns ``("preempted", results)`` so the process can exit for the
scheduler to reschedule.  A restarted server calls
``ServingEngine.restore`` (done here with ``resume=True``) and finishes
the snapshotted requests — greedy outputs bitwise-identical to an
uninterrupted run (``tests/unit/test_serving_slo.py`` kills the loop at
every serving fault-injection seam to prove it).
"""

from deepspeed_tpu.utils.logging import logger


def serve_resilient(srv, checkpoint_dir, agent=None, resume=True):
    """Run ``srv`` to completion or preemption.  Returns
    ``(status, results)`` with status ``"done"`` | ``"preempted"`` and
    ``results`` the merged ``{rid: output}`` map of every request that
    reached a terminal status during the call (``None`` outputs for
    non-COMPLETED terminals; typed detail via ``srv.result(rid)``).

    ``resume=True`` restores the newest valid snapshot under
    ``checkpoint_dir`` before the first iteration; pass ``False`` when
    the caller already ran ``srv.restore()`` itself (e.g. to dedup its
    own workload against the resumed requests).  On a clean finish an
    EMPTY snapshot is published so the next restart resumes nothing."""
    own_agent = agent is None
    if own_agent:
        from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
        agent = DSElasticAgent({}, checkpoint_dir=checkpoint_dir)
    agent.start()
    results = {}
    try:
        if resume:
            srv.restore(checkpoint_dir)
        while srv.work_pending():
            if agent.preempted:
                break
            results.update(srv.step())
        if agent.preempted:
            tag, snapped, finished = srv.preempt(checkpoint_dir)
            results.update(finished)
            logger.warning(f"[serving] preempted — snapshot {tag!r} "
                           f"holds {len(snapped)} request(s)")
            return "preempted", results
        # clean completion: publish an empty snapshot so a restarted
        # server does not re-resume already-finished work
        srv.snapshot(checkpoint_dir)
        return "done", results
    finally:
        if own_agent:
            agent.stop()
