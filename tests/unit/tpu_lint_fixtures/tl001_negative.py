"""TL001 negative fixture: the same syncs OFF the hot path, and benign
host-side casts ON it."""
import numpy as np
import jax
from deepspeed_tpu.tools.lint.hotpath import hot_path


def eval_epoch(losses):
    # not a hot path: syncing here is fine
    return [float(jax.device_get(l)) for l in losses]


@hot_path("fixture.train_step")
def train_step(params, batch, max_steps=8):
    steps = int(max_steps)           # bare-name cast: host API scalar
    n = int(np.prod((4, 8)))         # shape math, whitelisted
    return params, steps, n


def cold_helper(x):
    return x.item()                  # unreachable from any hot path
