"""Live device-memory telemetry (``docs/observability.md``, "Device
memory & roofline"): the sampler's owner reconciliation, the serving
engine's ``memory_telemetry`` wiring, and the acceptance contract —
telemetry on/off leaves serving outputs bitwise-identical and mints
zero new executables, the ``dstpu_device_memory_*`` gauges survive a
/metrics text-format round trip, flight-recorder dumps carry
``memory_sample`` events, and every knob defaults off.

Smallest serving model in the suite (the test_serving_trace
discipline): every assertion here is about HOST bookkeeping."""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.transformer import Transformer, TransformerConfig
from deepspeed_tpu.monitor.memwatch import (DeviceMemorySampler,
                                            MEMORY_SERIES,
                                            device_memory_record,
                                            tree_device_bytes)

SERVING = {"enabled": True, "num_slots": 2, "max_cache_len": 64,
           "prefill_chunk": 8, "prefill_token_budget": 16,
           "decode_block": 2}


@pytest.fixture(scope="module")
def shared_engine():
    model = Transformer(TransformerConfig(
        vocab_size=61, hidden_size=32, num_layers=1, num_heads=2,
        max_seq_len=64, use_flash_attention=False, dtype="float32"))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 61, (2, 12)),
                      jnp.int32)
    params = model.init(jax.random.key(0), {"input_ids": ids})
    eng = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "prefill_chunk_size": 8,
                       "serving": SERVING})
    eng.set_params(params)
    return eng


def _workload(rng, n=5):
    prompts = [rng.integers(1, 61, (int(p),)).astype(np.int32)
               for p in rng.integers(9, 21, (n,))]
    news = [int(x) for x in rng.integers(3, 9, (n,))]
    return prompts, news


def _fake_reader(in_use=1000, peak=1500, limit=16000):
    def read():
        return [{"device": "fake:0", "platform": "fake",
                 "bytes_in_use": in_use, "peak_bytes_in_use": peak,
                 "bytes_limit": limit, "limit_source": "runtime"}]
    return read


# --------------------------------------------------------------------- #
# Sampler unit behavior: reconciliation, cadence, watermark
# --------------------------------------------------------------------- #
def test_sampler_owner_reconciliation_and_unattributed():
    s = DeviceMemorySampler(
        interval_s=0.0, read_fn=_fake_reader(in_use=1000),
        owners_fn=lambda: {"params": 600, "kv": 150})
    sample = s.sample()
    assert sample["bytes_in_use"] == 1000
    assert sample["owned_bytes"] == 750
    assert sample["unattributed_bytes"] == 250
    assert sample["owners"] == {"params": 600, "kv": 150}
    # owners exceeding the reported total (a backend with no live
    # stats) clamp the gap at zero, never negative
    s2 = DeviceMemorySampler(interval_s=0.0, read_fn=_fake_reader(0, 0),
                             owners_fn=lambda: {"params": 999})
    assert s2.sample()["unattributed_bytes"] == 0


def test_sampler_interval_gating_and_flightrec():
    from deepspeed_tpu.inference.serving.flightrec import FlightRecorder
    fr = FlightRecorder(64)
    clock = [0.0]
    s = DeviceMemorySampler(interval_s=10.0, read_fn=_fake_reader(),
                            owners_fn=lambda: {"a": 1},
                            flightrec=fr, clock=lambda: clock[0])
    assert s.maybe_sample() is not None      # first call always samples
    assert s.maybe_sample() is None          # clock compare only
    clock[0] = 10.5
    assert s.maybe_sample() is not None
    assert s.samples == 2
    evs = [e for e in fr.snapshot()["events"]
           if e["ev"] == "memory_sample"]
    assert len(evs) == 2
    assert evs[0]["bytes_in_use"] == 1000
    assert evs[0]["owners"] == {"a": 1}
    assert s.last["peak_bytes_in_use"] == 1500


def test_tree_device_bytes_and_record_shape():
    tree = {"a": jnp.zeros((4, 8), jnp.float32),
            "b": [jnp.zeros((3,), jnp.int8), None]}
    assert tree_device_bytes(tree) == 4 * 8 * 4 + 3
    rec = device_memory_record()
    assert set(rec) == {"devices", "bytes_in_use", "peak_bytes_in_use",
                        "bytes_limit"}
    assert len(rec["devices"]) >= 1
    assert {"device", "bytes_in_use", "bytes_limit", "limit_source"} \
        <= set(rec["devices"][0])


# --------------------------------------------------------------------- #
# Acceptance: telemetry off/on — bitwise outputs, zero new executables
# --------------------------------------------------------------------- #
def test_memory_telemetry_off_on_bitwise_zero_new_execs(shared_engine,
                                                        tmp_path):
    eng = shared_engine
    rng = np.random.default_rng(11)
    prompts, news = _workload(rng)

    srv_off = eng.serve()
    assert srv_off._memwatch is None         # default off = seed engine
    assert srv_off.memory_snapshot() is None
    assert not any(k.startswith("hbm_") for k in srv_off.stats)
    n0 = len(eng._aot)
    rids = [srv_off.submit(p, max_new_tokens=n)
            for p, n in zip(prompts, news)]
    outs_off = srv_off.drain()
    execs_off = len(eng._aot) - n0
    srv_off.close()

    srv = eng.serve(memory_telemetry=True, memory_sample_interval_s=0.0,
                    flight_recorder=True,
                    flight_recorder_dir=str(tmp_path / "fr"))
    n1 = len(eng._aot)
    rids_on = [srv.submit(p, max_new_tokens=n)
               for p, n in zip(prompts, news)]
    outs_on = srv.drain()
    execs_on = len(eng._aot) - n1
    # the telemetry layer is host-side only: same executable count,
    # bitwise-identical outputs
    assert execs_on == execs_off, (execs_off, execs_on)
    for r_off, r_on in zip(rids, rids_on):
        np.testing.assert_array_equal(
            outs_off[r_off], outs_on[r_on],
            err_msg="memory telemetry changed serving outputs")

    # the run sampled every iteration (interval 0) into stats
    assert srv.stats["memory_samples"] > 0
    assert srv.stats["hbm_owned_bytes"] > 0
    owners = srv.memory_snapshot()["owners"]
    assert {"params", "kv_slots", "slot_state", "prefill_lanes"} \
        <= set(owners)
    assert owners["params"] == tree_device_bytes(eng._params)
    # flight recorder carries the trajectory + a dump round-trips it
    snap = srv.flightrec_snapshot()
    mem_evs = [e for e in snap["events"] if e["ev"] == "memory_sample"]
    assert mem_evs and "unattributed_bytes" in mem_evs[0]
    path = srv.dump_flightrec("memtest")
    with open(path) as f:
        dump = json.load(f)
    assert any(e["ev"] == "memory_sample" for e in dump["events"])
    srv.close()


# --------------------------------------------------------------------- #
# /metrics round trip for the dstpu_device_memory_* families
# --------------------------------------------------------------------- #
def test_metrics_round_trip_device_memory_gauges(shared_engine):
    import http.client
    from deepspeed_tpu.inference.serving.frontend import \
        ServingHTTPFrontend
    from tests.unit.test_serving_trace import parse_prometheus

    eng = shared_engine
    rng = np.random.default_rng(13)
    prompts, _ = _workload(rng, n=1)
    srv = eng.serve(memory_telemetry=True, memory_sample_interval_s=0.0)
    # deterministic nonzero device numbers regardless of backend: the
    # reader is injectable by design (the tier-1 CPU backend reports no
    # live stats)
    srv._memwatch._read = _fake_reader(in_use=5000, peak=7000,
                                       limit=16000)
    with ServingHTTPFrontend(srv) as fe:
        conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                          timeout=180)
        conn.request("POST", "/v1/generate", json.dumps(
            {"input_ids": [int(t) for t in prompts[0]],
             "max_new_tokens": 3}))
        assert conn.getresponse().status == 200
        conn.close()
        conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                          timeout=60)
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        assert resp.status == 200
        body = resp.read().decode()
        conn.close()
    srv.close()

    types, helps, samples = parse_prometheus(body)
    by_name = {}
    for name, labels, value in samples:
        by_name.setdefault(name, []).append((labels, value))
    # every declared family is present as a gauge with HELP/TYPE
    for fam in MEMORY_SERIES:
        assert types.get(fam) == "gauge", (fam, types.get(fam))
        assert fam in helps
        assert by_name.get(fam), fam
    in_use = by_name["dstpu_device_memory_bytes_in_use"]
    assert in_use[0][0]["device"] == "fake:0"
    assert in_use[0][1] == 5000.0
    limit = by_name["dstpu_device_memory_limit_bytes"][0]
    assert limit[0]["source"] == "runtime" and limit[1] == 16000.0
    owned = {la["owner"]: v for la, v in
             by_name["dstpu_device_memory_owned_bytes"]}
    assert {"params", "kv_slots", "slot_state", "prefill_lanes"} \
        <= set(owned)
    # reconciliation holds inside one scrape: unattributed =
    # max(0, in_use - sum(owned))
    unattr = by_name["dstpu_device_memory_unattributed_bytes"][0][1]
    assert unattr == max(0.0, 5000.0 - sum(owned.values()))
    # the stats gauges carry the watermark too
    assert by_name["dstpu_serving_hbm_peak_bytes"][0][1] >= 5000.0


def test_memory_knobs_default_off():
    from deepspeed_tpu.inference.serving.config import ServingConfig
    cfg = ServingConfig()
    assert cfg.memory_telemetry is False
    assert cfg.memory_sample_interval_s == 10.0
