"""Kernel-injection / model-conversion layer (reference ``module_inject/``).

On TPU "injection" = convert the HF torch checkpoint onto the framework's
flax Transformer and let XLA compile the fused program; TP slicing =
sharding annotations (AutoTP rules) instead of per-rank weight surgery.
"""

from deepspeed_tpu.module_inject.replace_module import (  # noqa: F401
    convert_hf_model, load_megatron_model, replace_transformer_layer,
    policy_for)
from deepspeed_tpu.module_inject.auto_tp import AutoTP, get_tp_rules  # noqa: F401
from deepspeed_tpu.module_inject.policy import HFPolicy  # noqa: F401
from deepspeed_tpu.module_inject.containers import (  # noqa: F401
    OPTPolicy, GPT2Policy, LlamaPolicy, BloomPolicy, GPTNeoXPolicy,
    GPTJPolicy, ALL_POLICIES)
