"""Test harness: simulate an 8-device TPU mesh on CPU.

The analog of the reference's distributed-without-a-cluster mechanism
(``tests/unit/common.py:89`` DistributedExec): instead of forking processes
per rank, JAX gives us N virtual devices in one process via
``--xla_force_host_platform_device_count`` — every sharding/collective code
path (GSPMD ZeRO, pipeline ppermute, MoE all_to_all) executes for real on the
CPU mesh.
"""

import os
import sys

# Must be set before jax *initializes a backend*.  The environment may import
# jax at interpreter start (sitecustomize) with JAX_PLATFORMS pinned to the
# real TPU platform, so overriding the env var alone is not enough — update
# the live jax config too.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ.setdefault("DSTPU_ACCELERATOR", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Persistent compilation cache: the suite is XLA-compile-dominated on the
# 1-core CI box; re-runs hit the cache and finish in roughly half the
# cold time (the CI-sharding analog of the reference's workflow split).
_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          ".jax_compile_cache")
jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
assert jax.device_count() == 8, f"expected 8 virtual CPU devices, got {jax.devices()}"

import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption("--run-slow", action="store_true", default=False,
                     help="run nightly-tier tests marked @pytest.mark.slow")


def pytest_collection_modifyitems(config, items):
    """Skip `slow` tests by default (CI time budget on the 1-core box) —
    unless --run-slow, an explicit -m expression, or a direct node-ID
    invocation asks for them."""
    if config.getoption("--run-slow") or config.option.markexpr:
        return
    # explicitly-named node IDs run even when slow — but only THOSE items,
    # not every slow test swept up by other path arguments in the same run
    explicit = [a for a in config.args if "::" in a]

    def _named(item):
        return any(item.nodeid == a or item.nodeid.startswith(a + "[")
                   or item.nodeid.startswith(a + "::") for a in explicit)

    skip = pytest.mark.skip(
        reason="slow (nightly tier); use --run-slow or -m slow")
    for item in items:
        if "slow" in item.keywords and not _named(item):
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _reset_topology():
    """Each test gets a fresh global topology (the analog of tearing down
    process groups between DistributedTest cases)."""
    from deepspeed_tpu.parallel import topology
    topology.reset_topology()
    yield
    topology.reset_topology()


@pytest.fixture
def eight_devices():
    import jax
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs
