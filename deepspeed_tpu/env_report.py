"""Environment / op-compatibility report — parity with reference
``deepspeed/env_report.py`` + ``bin/ds_report``."""

import sys


def main():
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.ops.op_builder import op_report
    from deepspeed_tpu.accelerator import get_accelerator

    accel = get_accelerator()
    lines = [
        "-" * 72,
        "DeepSpeed-TPU C++/Pallas op report",
        "-" * 72,
        op_report(),
        "-" * 72,
        "General environment:",
        f"deepspeed_tpu version ... {deepspeed_tpu.__version__}",
        f"jax version ............. {jax.__version__}",
        f"default backend ......... {jax.default_backend()}",
        f"accelerator ............. {accel.device_name()}",
        f"local devices ........... {accel.device_count()}",
        f"global devices .......... {accel.global_device_count()}",
        f"bf16 supported .......... {accel.is_bf16_supported()}",
        f"python .................. {sys.version.split()[0]}",
    ]
    try:
        import flax
        import optax
        lines.append(f"flax / optax ............ {flax.__version__} / {optax.__version__}")
    except ImportError:
        pass
    report = "\n".join(lines)
    print(report)
    return report


def cli_main():
    main()


if __name__ == "__main__":
    main()
