"""``serving`` config block — continuous-batching serving engine knobs
(``docs/serving.md``).  Kept import-light: ``inference/config.py`` embeds
this model, and the serving engine itself is imported lazily."""

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class ServingConfig(DeepSpeedConfigModel):
    """Knobs for :class:`deepspeed_tpu.inference.serving.ServingEngine`
    (``engine.serve()``).  Default off = current behavior: nothing in the
    whole-batch ``generate()`` path changes unless ``serve()`` is called
    (the explicit opt-in); ``enabled`` documents the deployment intent in
    ops configs.  ``ServingEngine.warmup()`` precompiles the serving
    programs."""
    enabled: bool = False
    # fixed-shape KV slot lanes: the ONE decode-step program is compiled
    # for exactly this many cache rows; requests map onto freed lanes
    num_slots: int = 8
    # per-slot cache positions (rounded up to a multiple of 8 — the fused
    # decode kernel's sublane alignment); every request must satisfy
    # ceil(prompt/chunk)*chunk <= max_cache_len and
    # prompt + max_new_tokens <= max_cache_len
    max_cache_len: int = 2048
    # admission-prefill chunk: prompts stream through the engine's donated
    # per-chunk executable in blocks of this many tokens (aligned to a
    # multiple of 8, floor 8, cap 512 like prefill_chunk_size)
    prefill_chunk: int = 128
    # prefill tokens spent per scheduler iteration before decode resumes
    # (the Sarathi/Orca-style interleave bound); 0 = finish each admission's
    # prefill in one iteration
    prefill_token_budget: int = 512
    # decode steps per host round trip: one compiled program advances all
    # slots `decode_block` tokens between scheduling points.  Larger blocks
    # amortize dispatch latency; retired slots idle for at most
    # decode_block-1 steps before the scheduler reclaims them
    decode_block: int = 4
    # admission order: "fcfs" (arrival) | "shortest_first" (shortest
    # prompt first — lowers mean time-to-first-token under backlog)
    admission: str = "fcfs"
    # ---- paged KV cache (docs/serving.md "Paged KV cache") ----
    # paged=True replaces the per-slot monolithic lanes with a shared
    # page pool + per-slot block tables (traced args — still ONE decode
    # executable per server): HBM cost becomes num_pages * page_size
    # instead of num_slots * max_cache_len, shared prefixes are stored
    # once, and capacity pressure degrades into admission backpressure
    # instead of an allocation cliff.  Default off = seed behavior.
    paged: bool = False
    # positions per page (rounded up to a multiple of 8 — sublane
    # alignment — floor 8).  Smaller pages waste less per-request tail
    # but cost a bigger table and finer gathers
    page_size: int = 64
    # physical pages in the pool, INCLUDING the reserved trash page 0;
    # 0 = auto: num_slots * ceil(max_cache_len/page_size) + 1 (full
    # worst-case capacity — no savings, no pressure).  Size it below
    # auto to actual demand for the HBM win; admission then waits for
    # free pages under pressure (queue backpressure, never corruption)
    num_pages: int = 0
    # Pallas paged-attention kernels (paged only): decode attends
    # straight over the page pool through the block table (split-K
    # across pages, online softmax, int8-KV dequant fused into the page
    # load) and admission prefill takes the paged chunk kernel — the
    # BENCH_r04 bs128 decode cliff fix.  False = the pre-kernel gather
    # path (take_along_axis virtual view per layer, for A/B benching);
    # the registry then warns once and stats["paged_attention_fallback"]
    # counts every decode dispatch that took the slow path
    paged_kernel: bool = True
    # copy-on-write prefix sharing (paged only): page-aligned leading
    # blocks of a prompt that hash-match an earlier prompt map to the
    # SAME physical pages, prefilled once; divergence re-prefills at
    # most one page.  Unreferenced prefix pages evict LRU under pool
    # pressure
    prefix_cache: bool = True
    # ---- speculative decoding (docs/serving.md "Speculative
    # decoding") ----
    # speculative=True: a small DRAFT model proposes spec_k tokens per
    # live slot per dispatch and the target model verifies all of them
    # in ONE batched forward — up to spec_k+1 tokens committed per
    # target forward, greedy outputs bitwise-identical to
    # non-speculative serving.  Requires a draft model
    # (engine.serve(draft_module=..., draft_params=...) or
    # spec_draft_model="self") and greedy decoding (do_sample=False).
    # Supersedes decode_block (the verify window is the block).  Default
    # off = seed behavior.
    speculative: bool = False
    # draft tokens proposed per verify window; each window commits
    # between 1 and spec_k+1 tokens.  Each slot lane reserves spec_k-1
    # extra tail positions for the window's writes, so requests must
    # satisfy prompt + max_new_tokens + spec_k - 1 <= max_cache_len
    spec_k: int = 4
    # draft model source when serve() is not handed one explicitly:
    # "self" = the target model drafts for itself (accept rate 1.0 under
    # greedy — the dispatch/batched-verify ceiling; doubles KV + decode
    # compute), or an OPT preset name ("opt-125m") built against the
    # target's vocab — pass its trained weights via
    # serve(draft_params=...), else they are RANDOMLY initialized
    # (accept rate ~0; smoke/bench floor only, warned loudly)
    spec_draft_model: str = ""
    # sampling applied to every request (greedy when do_sample=False);
    # per-request eos_token_id/max_new_tokens ride the slot state instead
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    # ---- robustness / SLO knobs (docs/serving.md "Robustness & SLOs",
    # inference/serving/slo.py) — every default = seed behavior ----
    # bounded-queue admission control: submit() beyond this depth either
    # rejects (QueueFull) or blocks running scheduler iterations inline
    # until a spot frees; 0 = unbounded (seed behavior)
    max_queue_depth: int = 0
    queue_policy: str = "reject"          # "reject" | "block"
    # default per-request wall-clock deadline (seconds from submit);
    # submit(deadline_s=...) overrides per request; 0 = no deadline.
    # Expired-while-queued requests are SHED before ever occupying a
    # slot; in-slot expiry retires at the next scheduling point
    default_deadline_s: float = 0.0
    # dispatch circuit breaker: this many CONSECUTIVE failed
    # decode/admit/prefill dispatches trip it open — failures are
    # absorbed (requests -> ABORTED), admission stops and submit()
    # rejects with reason until the cooldown's half-open probe succeeds.
    # 0 = off (seed behavior: dispatch failures propagate to the caller)
    breaker_threshold: int = 0
    breaker_cooldown_s: float = 30.0
    # drain() wall-clock timeout: raise DrainTimeout with per-slot
    # diagnostics instead of spinning forever on a wedged scheduler;
    # 0 = off (seed behavior)
    drain_timeout_s: float = 0.0
    # ---- network front end (docs/serving.md "Network front end") ----
    # admission priority lanes layered on the fcfs/shortest_first queue:
    # submit(priority=p) with 0 <= p < priority_lanes, 0 = most urgent.
    # 1 (default) = no lanes, seed admission order
    priority_lanes: int = 1
    # starvation bound for the lanes: a queued request's effective
    # priority improves one lane per this many seconds waited, so the
    # lowest lane reaches lane 0 after (priority_lanes-1)*aging seconds
    # and fcfs/shortest_first order takes over; 0 = no aging (strict
    # lanes — low priority CAN starve under sustained high-priority load)
    priority_aging_s: float = 30.0
    # multi-tenant fairness: per-client_id token-rate accounting
    # (admitted prefill + generated tokens, exponentially decaying
    # window) feeding admission control — submit() from a client whose
    # window usage exceeds fairness_tokens_per_s * fairness_window_s
    # raises QueueFull (HTTP 429) while other clients keep flowing.
    # 0 = off (seed behavior)
    fairness_tokens_per_s: float = 0.0
    # decay time constant (seconds) of the fairness window: usage decays
    # by 1/e per window, budget = fairness_tokens_per_s * window
    fairness_window_s: float = 10.0
    # graceful-preemption drain budget (preempt()): keep decoding
    # in-flight slots for up to this many seconds before snapshotting
    # the remainder; 0 = snapshot immediately, no drain
    drain_budget_s: float = 30.0
    # ---- observability (docs/observability.md) — every default = seed
    # behavior: zero spans, zero histograms, zero ring events ----
    # per-request span tracing: record a span tree per request (submit ->
    # queue wait -> prefill chunks -> admit -> decode/spec dispatches ->
    # terminal) at the existing scheduler seams, export Chrome
    # trace-event JSON via srv.dump_trace(path) (Perfetto: one track per
    # slot + scheduler/queue tracks), attach a queue/prefill/decode/host
    # latency breakdown to every RequestResult, and feed the
    # TTFT/TBT/queue-wait/dispatch/lock-wait histograms /metrics
    # exposes.  Host-side only: no new jitted programs, greedy outputs
    # bitwise-identical either way
    tracing: bool = False
    # span-ring bound (oldest spans fall off; the dump records how many
    # were dropped)
    trace_max_spans: int = 100000
    # flight recorder: a bounded ring of recent structured scheduler
    # events (dispatch begin/end, admit/shed/cancel/abort decisions,
    # breaker transitions, lock-wait samples, fault-injection hits)
    # that auto-dumps to JSON on breaker-open, DrainTimeout,
    # ConcurrencyViolation and scheduler-thread death, and on demand via
    # GET /debug/flightrec, SIGUSR2 or srv.dump_flightrec().  The ring
    # has its OWN lock — readers never contend the engine lock
    flight_recorder: bool = False
    # ring capacity in events (memory is bounded; ~300 bytes/event)
    flight_recorder_events: int = 2048
    # auto-dump directory; "" = <tmpdir>/dstpu_flightrec
    flight_recorder_dir: str = ""
    # on-demand device-level profiling: POST /debug/profile?secs=N runs
    # jax.profiler for N seconds and returns the trace directory
    # (Perfetto/TensorBoard-loadable).  Off by default: profiling is a
    # debug affordance, not a production endpoint
    profile_endpoint: bool = False
    # live device-memory telemetry (docs/observability.md, "Device
    # memory & roofline"): a host-side sampler reads per-device
    # bytes_in_use/peak/limit through the accelerator's canonical
    # memory reader at scheduler seams, reconciles the engine's known
    # owners (page pool, KV/draft workspaces, params, lanes, slot
    # state) against the device total into an unattributed-bytes gap,
    # exports dstpu_device_memory_* gauges on /metrics, records
    # memory_sample events in the flight-recorder ring (when that is
    # on), and stamps a peak-HBM watermark into stats.  Host-side only
    # — zero new executables, outputs bitwise-identical either way.
    # Default off = seed behavior
    memory_telemetry: bool = False
    # seconds between memory samples (a clock compare between samples;
    # each sample is one PJRT memory_stats() host call per device)
    memory_sample_interval_s: float = 10.0
