"""Import sweep: every module in the package must import cleanly (catches
import-time breakage anywhere in the tree — the analog of the reference's
pre-compile op check CI)."""

import importlib
import pkgutil

import pytest

import deepspeed_tpu


def _all_modules():
    mods = []
    for m in pkgutil.walk_packages(deepspeed_tpu.__path__,
                                   prefix="deepspeed_tpu."):
        mods.append(m.name)
    return mods


@pytest.mark.parametrize("name", _all_modules())
def test_module_imports(name):
    importlib.import_module(name)
