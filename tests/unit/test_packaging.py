"""Installable packaging (reference ``setup.py:292-295``): ``pip install``
must produce working console entry points with no repo-root ``sys.path``
insertion.  The install goes to a throwaway ``--prefix`` so the live
environment is untouched; ``--no-deps --no-build-isolation`` keeps it
fully offline."""

import glob
import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))


@pytest.mark.slow
def test_pip_install_console_scripts(tmp_path):
    prefix = tmp_path / "prefix"
    proc = subprocess.run(
        [sys.executable, "-m", "pip", "install", "--no-deps",
         "--no-build-isolation", "--prefix", str(prefix), REPO],
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]

    bindir = prefix / "bin"
    installed = {os.path.basename(p) for p in glob.glob(str(bindir / "*"))}
    for script in ("deepspeed", "ds", "dsr", "deepspeed.pt", "ds_report",
                   "ds_bench", "ds_elastic", "ds_ssh", "ds_ckpt"):
        assert script in installed, f"{script} missing from {installed}"

    site = glob.glob(str(prefix / "lib" / "python*" / "site-packages"))
    assert site, "no site-packages under the install prefix"
    env = dict(os.environ)
    env["PYTHONPATH"] = site[0]
    env.pop("BENCH_MODEL", None)
    # the installed package must import and the CLI must answer --help
    # WITHOUT the repo on sys.path (cwd is / so '' doesn't leak it in)
    out = subprocess.run(
        [str(bindir / "deepspeed"), "--help"], env=env, cwd="/",
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-3000:]
    assert "launcher" in (out.stdout + out.stderr).lower() or \
        "usage" in (out.stdout + out.stderr).lower()

    out = subprocess.run(
        [str(bindir / "ds_report")], env=env, cwd="/",
        capture_output=True, text=True, timeout=180)
    assert out.returncode == 0, out.stderr[-3000:]
    assert "deepspeed" in out.stdout.lower()
