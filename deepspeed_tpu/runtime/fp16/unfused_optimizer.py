"""FP16_UnfusedOptimizer — reference ``runtime/fp16/unfused_optimizer.py:23``:
the per-tensor (non-multi-tensor-apply) variant of FP16_Optimizer, kept for
optimizers without fused kernels.

On TPU the fused/unfused distinction dissolves — XLA fuses the per-leaf
update loop either way — so this subclass differs only in applying updates
leaf-by-leaf with per-leaf overflow short-circuiting (norm clipping per
group, reference behavior), and exists for API parity.
"""

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.fp16.fused_optimizer import FP16_Optimizer


class FP16_UnfusedOptimizer(FP16_Optimizer):

    def _step_fn(self):
        clip = self.clip_grad
        scaler = self.loss_scaler
        opt = self.optimizer

        def step(masters, opt_state, scaler_state, grads, step_no):
            inv = 1.0 / scaler_state.scale
            grads = jax.tree.map(lambda g: g.astype(jnp.float32) * inv, grads)
            found_inf = jnp.logical_not(jnp.all(jnp.stack(
                [jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(grads)])))
            # report the PRE-clip global norm (same contract as the fused
            # wrapper), then clip per leaf (the reference clips per group)
            gnorm = jnp.sqrt(sum(jnp.sum(g * g)
                                 for g in jax.tree.leaves(grads)))
            if clip > 0:
                grads = jax.tree.map(
                    lambda g: g * jnp.minimum(
                        1.0, clip / (jnp.linalg.norm(g.ravel()) + 1e-6)),
                    grads)
            new_masters, new_opt = opt.update(grads, opt_state, masters,
                                              step=step_no)
            keep = lambda new, old: jax.tree.map(
                lambda n, o: jnp.where(found_inf, o, n), new, old)
            return (keep(new_masters, masters), keep(new_opt, opt_state),
                    scaler.update(scaler_state, found_inf), found_inf, gnorm)

        return jax.jit(step, donate_argnums=(0, 1))
