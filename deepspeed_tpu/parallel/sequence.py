"""Sequence/context parallelism — long-context attention over a seq-sharded
mesh axis.

The reference (v0.9.3) predates DeepSpeed-Ulysses/ring attention (SURVEY §5:
absent; long context = sparse attention + curriculum).  On TPU sequence
sharding is idiomatic, so this module goes beyond parity with both standard
schemes, as differentiable primitives callable inside ``shard_map`` over an
``sp`` axis:

* ``ulysses_attention`` — DeepSpeed-Ulysses style: all_to_all scatters heads
  / gathers sequence, each device runs FULL-sequence attention on H/sp heads
  (the Pallas flash kernel unchanged), all_to_all back.  Comm = 2 all_to_alls
  of activation size; attention math unchanged.  Requires H % sp == 0.
* ``ring_attention`` — KV blocks rotate around the ring (ppermute) while
  queries stay put; online-softmax accumulation combines per-block partial
  results, O(S/sp) live KV per device with no head-count constraint.
  Causal block skipping: a fully-future KV block contributes nothing and is
  skipped via ``jnp.where`` masking of the whole block.

Both are pure jax (scan + collectives) so jax.grad differentiates them;
ring's backward replays the rotation in reverse via autodiff through
``ppermute``.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


# --------------------------------------------------------------------- #
# Ulysses (all-to-all) sequence parallelism
# --------------------------------------------------------------------- #
def ulysses_attention(q, k, v, axis="sp", causal=True, attn_fn=None):
    """q/k/v: this device's [B, S_local, H, D] shard.  Returns the local
    [B, S_local, H, D] output shard."""
    if attn_fn is None:
        from deepspeed_tpu.ops.transformer.flash_attention import (
            flash_attention, pallas_supported)
        if pallas_supported():
            attn_fn = flash_attention
        else:
            from deepspeed_tpu.models.transformer import reference_attention
            attn_fn = reference_attention
    # [B, S/W, H, D] -> [B, S, H/W, D]: scatter heads, gather sequence
    qg = lax.all_to_all(q, axis, split_axis=2, concat_axis=1, tiled=True)
    kg = lax.all_to_all(k, axis, split_axis=2, concat_axis=1, tiled=True)
    vg = lax.all_to_all(v, axis, split_axis=2, concat_axis=1, tiled=True)
    out = attn_fn(qg, kg, vg, causal=causal)
    # back: scatter sequence, gather heads
    return lax.all_to_all(out, axis, split_axis=1, concat_axis=2, tiled=True)


# --------------------------------------------------------------------- #
# Ring attention
# --------------------------------------------------------------------- #
def _block_attn(q, k, v, scale, mask):
    """One KV block's contribution: returns (scores_max, exp-sum, weighted
    values) in fp32 for online combination.  q/k/v: [B, Sq, H, D]."""
    s = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)              # [B,H,Sq,1]
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(s - m_safe)
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)              # [B,H,Sq,1]
    o = jnp.einsum("bhst,bthd->bhsd", p, v.astype(jnp.float32))
    return m_safe, l, o


def ring_attention(q, k, v, axis="sp", axis_size=None, causal=True,
                   scale=None):
    """Ring flash attention over mesh axis ``axis``.

    q/k/v: [B, S_local, H, D] shards (sequence dim sharded contiguously in
    rank order).  KV rotates ``axis_size`` times; a numerically stable online
    softmax merges block results.  Memory: one KV shard + one [B,H,Sl,Sl]
    block of scores live at a time.
    """
    if axis_size is None:
        axis_size = lax.psum(1, axis)
    W = int(axis_size)
    B, Sl, H, D = q.shape
    if scale is None:
        scale = 1.0 / float(np.sqrt(D))
    r = lax.axis_index(axis)
    perm = [(j, (j + 1) % W) for j in range(W)]

    rows = jnp.arange(Sl)[:, None]      # local q positions
    cols = jnp.arange(Sl)[None, :]      # local kv positions

    def block_mask_for(src):
        if not causal:
            return None
        # block-level causality: strictly-future chunk → fully masked;
        # same chunk → intra-block causal; past chunk → fully visible
        intra = rows >= cols
        return jnp.where(src == r, intra[None, None],
                         jnp.broadcast_to(src < r, (1, 1, Sl, Sl)))

    def merge(acc, blk):
        m_acc, l_acc, o_acc = acc
        m_b, l_b, o_b = blk
        m_new = jnp.maximum(m_acc, m_b)
        c_acc = jnp.exp(m_acc - m_new)
        c_b = jnp.exp(m_b - m_new)
        return (m_new, l_acc * c_acc + l_b * c_b,
                o_acc * c_acc + o_b * c_b)

    # local chunk first, then rotate W-1 times with the ppermute at the loop
    # head — no wasted final rotation
    acc0 = _block_attn(q, k, v, scale, block_mask_for(r))

    def body(carry, i):
        m_acc, l_acc, o_acc, k_cur, v_cur = carry
        k_cur = lax.ppermute(k_cur, axis, perm)
        v_cur = lax.ppermute(v_cur, axis, perm)
        src = jnp.mod(r - i, W)   # chunk held after i rotations
        blk = _block_attn(q, k_cur, v_cur, scale, block_mask_for(src))
        m_new, l_new, o_new = merge((m_acc, l_acc, o_acc), blk)
        return (m_new, l_new, o_new, k_cur, v_cur), None

    (m, l, o, _, _), _ = lax.scan(body, (*acc0, k, v), jnp.arange(1, W))
    out = o / jnp.maximum(l, 1e-20)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)    # [B, Sl, H, D]


# --------------------------------------------------------------------- #
# dispatcher + mesh-level wrapper
# --------------------------------------------------------------------- #
def sequence_parallel_attention(q, k, v, impl="ulysses", axis="sp",
                                axis_size=None, causal=True):
    if impl == "ulysses":
        return ulysses_attention(q, k, v, axis=axis, causal=causal)
    if impl == "ring":
        return ring_attention(q, k, v, axis=axis, axis_size=axis_size,
                              causal=causal)
    raise ValueError(f"unknown sequence-parallel impl {impl!r} "
                     "(choices: ulysses, ring)")


def shard_map_attention(mesh, impl="ulysses", axis="sp", causal=True,
                        batch_axes=None, head_axes=None):
    """Build a [B, S, H, D] → [B, S, H, D] function where S is sharded over
    ``axis`` of ``mesh`` — the entry point for model integration (callable
    under jit; XLA sees the collectives explicitly).

    ``batch_axes``/``head_axes``: mesh axes the batch / head dims are sharded
    over (dp, tp).  Declaring them keeps shard_map from all-gathering the
    dp-sharded batch onto every device — each device computes only its own
    batch/head shard, with collectives riding the sp axis alone."""
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.utils.jax_compat import shard_map as _shard_map

    def smap(f, **kw):
        return _shard_map(f, mesh=kw["mesh"], in_specs=kw["in_specs"],
                          out_specs=kw["out_specs"], check_vma=False)

    axis_size = int(np.prod([mesh.shape[a] for a in
                             ((axis,) if isinstance(axis, str) else axis)]))
    spec = P(batch_axes, axis, head_axes, None)

    def local(q, k, v):
        return sequence_parallel_attention(q, k, v, impl=impl, axis=axis,
                                           axis_size=axis_size, causal=causal)

    return smap(local, mesh=mesh, in_specs=(spec, spec, spec),
                out_specs=spec)
