"""Autotuning tests (analog of reference ``tests/unit/autotuning/test_autotuning.py``)."""

import json
import os

import numpy as np
import pytest

from deepspeed_tpu.autotuning import Autotuner, Experiment, ResourceManager
from deepspeed_tpu.autotuning.cost_model import estimate_zero_memory
from deepspeed_tpu.autotuning.tuner import (GridSearchTuner, ModelBasedTuner,
                                            RandomTuner)
from deepspeed_tpu.autotuning.utils import (dict_deep_update, powers_of_two,
                                            resize_batch)

from simple_model import SimpleModel, random_batch


def _base_config(tmp_path, **autotuning):
    at = {"enabled": True, "results_dir": str(tmp_path / "results"),
          "exps_dir": str(tmp_path / "exps"),
          "start_profile_step": 1, "end_profile_step": 2}
    at.update(autotuning)
    return {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "autotuning": at,
    }


def test_memory_model_monotone_in_stage():
    """Higher ZeRO stages shard more state → monotonically less memory."""
    mems = [estimate_zero_memory(int(1e9), dp_size=8, zero_stage=s,
                                 micro_batch_size=1) for s in (0, 1, 2, 3)]
    assert mems == sorted(mems, reverse=True)
    assert mems[0] > 3 * mems[3]


def test_utils():
    assert powers_of_two(1, 8) == [1, 2, 4, 8]
    assert powers_of_two(2, 5) == [2, 4]
    merged = dict_deep_update({"a": {"b": 1, "c": 2}}, {"a": {"b": 9}, "d": 3})
    assert merged == {"a": {"b": 9, "c": 2}, "d": 3}
    b = resize_batch({"x": np.zeros((2, 4))}, 8)
    assert b["x"].shape == (8, 4)


def test_tuner_strategies_order():
    """Grid preserves order; random permutes; both visit everything."""
    exps = [Experiment(f"e{i}", {"train_micro_batch_size_per_gpu": 2 ** i})
            for i in range(5)]
    rm = ResourceManager(lambda exp: {"throughput": float(
        exp.config["train_micro_batch_size_per_gpu"])})
    best, val = GridSearchTuner(list(exps), rm, "throughput").tune(n_trials=50)
    assert best.name == "e4" and val == 16.0

    rm2 = ResourceManager(lambda exp: {"throughput": float(
        exp.config["train_micro_batch_size_per_gpu"])})
    exps2 = [Experiment(f"e{i}", {"train_micro_batch_size_per_gpu": 2 ** i})
             for i in range(5)]
    best2, val2 = RandomTuner(list(exps2), rm2, "throughput", seed=3).tune(n_trials=50)
    assert val2 == 16.0


def test_model_based_tuner_prefers_predicted_best():
    """After warmup the surrogate should route trials toward larger mbs
    (throughput grows with mbs in this synthetic space)."""
    exps = [Experiment(f"e{i}", {"train_micro_batch_size_per_gpu": 2 ** i})
            for i in range(8)]
    rm = ResourceManager(lambda exp: {"throughput": float(
        np.log2(exp.config["train_micro_batch_size_per_gpu"]) + 1)})
    tuner = ModelBasedTuner(list(exps), rm, "throughput", warmup=3)
    best, val = tuner.tune(n_trials=6)
    assert val is not None
    # 6 trials over an 8-point space with a perfectly-learnable trend must
    # find the max (128 → throughput 8.0)
    assert val == 8.0


def test_autotuner_end_to_end(tmp_path):
    model = SimpleModel(hidden_dim=8, nlayers=1)
    # max_train_batch_size bounds the GLOBAL batch: 32 over the 8-device
    # mesh → per-chip micro-batch candidates up to 4
    cfg = _base_config(tmp_path, num_tuning_micro_batch_sizes=2,
                      max_train_batch_size=32, fast=True)
    tuner = Autotuner(model, cfg, random_batch(batch_size=2, dim=8, classes=8),
                      zero_stages=[0, 1])
    best = tuner.tune()
    assert best is not None
    assert best["train_micro_batch_size_per_gpu"] in (2, 4)
    assert best["zero_optimization"]["stage"] in (0, 1)
    # results persisted for the user (reference writes ds_config_optimal.json)
    results = json.load(open(os.path.join(cfg["autotuning"]["results_dir"],
                                          "summary.json")))
    assert results["best_exp"] is not None
    assert len(results["experiments"]) >= 2
    assert os.path.exists(os.path.join(cfg["autotuning"]["results_dir"],
                                       "ds_config_optimal.json"))
    # every experiment measured a real throughput and a flops estimate
    for e in results["experiments"]:
        assert e["results"].get("throughput", 0) > 0, e
        assert e["results"].get("flops", 0) > 0, e


def test_autotuner_flops_metric(tmp_path):
    """metric='flops' must select a config (reference supports the FLOPS
    metric; results must carry the key the tuner ranks by)."""
    model = SimpleModel(hidden_dim=8, nlayers=1)
    cfg = _base_config(tmp_path, metric="flops", num_tuning_micro_batch_sizes=2,
                      max_train_batch_size=32)
    tuner = Autotuner(model, cfg, random_batch(batch_size=2, dim=8, classes=8),
                      zero_stages=[0])
    best = tuner.tune()
    assert best is not None
    assert tuner.best_metric_val > 0


def test_resource_manager_parallel_slots():
    """Parallel dispatch over the slot pool (reference ResourceManager
    multi-node scheduling, ``scheduler.py:33``): experiments genuinely
    overlap (peak in-flight > 1), every run gets a slot, results recorded."""
    import threading
    import time
    lock = threading.Lock()
    state = {"live": 0, "peak": 0}
    barrier = threading.Barrier(4, timeout=10)

    def run(exp):
        with lock:
            state["live"] += 1
            state["peak"] = max(state["peak"], state["live"])
        if exp.name in ("p0", "p1", "p2", "p3"):
            # first wave: prove 4 runs are in flight simultaneously
            barrier.wait()
        time.sleep(0.02)
        with lock:
            state["live"] -= 1
        return {"throughput": 1.0}

    exps = [Experiment(f"p{i}", {}) for i in range(8)]
    rm = ResourceManager(run, num_workers=4)
    rm.schedule_experiments(exps)
    assert state["peak"] == 4, f"peak concurrency {state['peak']} != 4 slots"
    assert all(e.status == "done" for e in exps)
    assert all(e.slot is not None for e in exps)
    assert all(e.to_dict()["duration_s"] is not None for e in exps)


def test_resource_manager_early_stop_skips_pending():
    """Once the early-stop predicate fires, not-yet-started experiments are
    marked SKIPPED and never run (the reference cancels pending jobs)."""
    import time
    ran = []

    def run(exp):
        ran.append(exp.name)
        time.sleep(0.05)
        return {"throughput": 1.0}

    exps = [Experiment(f"s{i}", {}) for i in range(10)]
    rm = ResourceManager(run, num_workers=2)
    rm.schedule_experiments(
        exps, early_stop_fn=lambda fin: sum(
            1 for e in fin if e.status == "done") >= 3)
    skipped = [e for e in exps if e.status == "skipped"]
    done = [e for e in exps if e.status == "done"]
    assert len(done) >= 3
    assert skipped, "early stop never cancelled pending experiments"
    assert all(e.name not in ran for e in skipped)

    # sequential (1-slot) path has the same semantics
    exps2 = [Experiment(f"q{i}", {}) for i in range(6)]
    rm2 = ResourceManager(lambda e: {"throughput": 1.0}, num_workers=1)
    rm2.schedule_experiments(exps2, early_stop_fn=lambda fin: len(fin) >= 2)
    assert [e.status for e in exps2] == ["done", "done"] + ["skipped"] * 4


def test_resource_manager_timeout_and_failure_status():
    import time

    def run(exp):
        if exp.name == "slow":
            time.sleep(0.2)
            return {"throughput": 1.0}
        raise RuntimeError("boom")

    exps = [Experiment("slow", {}), Experiment("bad", {})]
    rm = ResourceManager(run, num_workers=1, exp_timeout=0.05)
    rm.schedule_experiments(exps)
    assert exps[0].status == "timeout"
    assert "exp_timeout" in exps[0].error
    # a straggler's results are dropped: the tuner must never select it
    assert exps[0].results == {}
    assert exps[1].status == "failed"
    assert "boom" in exps[1].error


def test_model_based_autotuner_end_to_end_on_mesh(tmp_path):
    """The model-based tuner drives the REAL engine on the CPU mesh and its
    pick matches the known best (max measured metric over every candidate it
    evaluated) — VERDICT r1 #7 validation."""
    model = SimpleModel(hidden_dim=8, nlayers=1)
    cfg = _base_config(tmp_path, tuner_type="model_based",
                      num_tuning_micro_batch_sizes=2,
                      max_train_batch_size=32, fast=True)
    tuner = Autotuner(model, cfg, random_batch(batch_size=2, dim=8, classes=8),
                      zero_stages=[0, 1])
    best = tuner.tune()
    assert best is not None
    measured = [e.results.get("throughput")
                for e in tuner.rm.finished_experiments
                if e.results.get("throughput") is not None]
    assert measured and tuner.best_metric_val == max(measured)
    assert isinstance(tuner._build_tuner([]), ModelBasedTuner)


def test_autotuner_memory_prune(tmp_path, monkeypatch):
    """A tiny memory budget must prune the whole space without running."""
    monkeypatch.setenv("DSTPU_HBM_BYTES", "64")
    model = SimpleModel(hidden_dim=8, nlayers=1)
    cfg = _base_config(tmp_path)
    tuner = Autotuner(model, cfg, random_batch(batch_size=2, dim=8, classes=8))
    assert tuner.tune() is None
    assert tuner.rm.finished_experiments == []
