"""Memory-mapped indexed dataset — reference
``runtime/data_pipeline/data_sampling/indexed_dataset.py`` (617 LoC,
Megatron-LM format): a ``.bin`` of concatenated token arrays plus a ``.idx``
with dtype/sizes/pointers, read via np.memmap so a multi-TB corpus costs no
RAM.

Format (little-endian):
  idx:  magic ``DSTPUIDX`` | version u32 | dtype_code u8 | count u64 |
        sizes u32[count] | pointers u64[count]
  bin:  raw sample arrays back to back
"""

import os
import struct

import numpy as np

_MAGIC = b"DSTPUIDX"
_VERSION = 1

_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
           5: np.int64, 6: np.float32, 7: np.float64, 8: np.uint16}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix):
    return prefix + ".bin"


def index_file_path(prefix):
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    """Streaming writer (reference ``MMapIndexedDatasetBuilder``)."""

    def __init__(self, out_prefix, dtype=np.int32):
        self.prefix = out_prefix
        self.dtype = np.dtype(dtype)
        self._bin = open(data_file_path(out_prefix), "wb")
        self.sizes = []
        self.pointers = []
        self._offset = 0

    def add_item(self, tokens):
        arr = np.asarray(tokens, dtype=self.dtype)
        self._bin.write(arr.tobytes(order="C"))
        self.pointers.append(self._offset)
        self.sizes.append(arr.size)
        self._offset += arr.nbytes

    def merge_file_(self, other_prefix):
        """Append another indexed dataset (reference ``merge_file_`` used by
        parallel preprocessing workers) — a single streamed byte copy of the
        .bin plus offset-shifted index arithmetic, no per-sample decode."""
        other = MMapIndexedDataset(other_prefix)
        assert other.dtype == self.dtype, \
            f"dtype mismatch merging {other_prefix}: " \
            f"{other.dtype} vs builder {self.dtype}"
        base = self._offset
        with open(data_file_path(other_prefix), "rb") as src:
            while True:
                buf = src.read(16 << 20)
                if not buf:
                    break
                self._bin.write(buf)
                self._offset += len(buf)
        self.sizes.extend(int(s) for s in other.sizes)
        self.pointers.extend(base + int(p) for p in other.pointers)

    def finalize(self):
        self._bin.close()
        with open(index_file_path(self.prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<I", _VERSION))
            f.write(struct.pack("<B", _DTYPE_CODES[self.dtype]))
            f.write(struct.pack("<Q", len(self.sizes)))
            f.write(np.asarray(self.sizes, np.uint32).tobytes())
            f.write(np.asarray(self.pointers, np.uint64).tobytes())


class MMapIndexedDataset:
    """Zero-copy reader (reference ``MMapIndexedDataset``)."""

    def __init__(self, prefix):
        with open(index_file_path(prefix), "rb") as f:
            assert f.read(8) == _MAGIC, f"bad index magic in {prefix}.idx"
            (version,) = struct.unpack("<I", f.read(4))
            assert version == _VERSION
            (code,) = struct.unpack("<B", f.read(1))
            self.dtype = np.dtype(_DTYPES[code])
            (count,) = struct.unpack("<Q", f.read(8))
            self.sizes = np.frombuffer(f.read(4 * count), np.uint32)
            self.pointers = np.frombuffer(f.read(8 * count), np.uint64)
        self._data = np.memmap(data_file_path(prefix), mode="r", dtype=np.uint8)
        self.prefix = prefix

    def __len__(self):
        return len(self.sizes)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        ptr, size = int(self.pointers[i]), int(self.sizes[i])
        raw = self._data[ptr:ptr + size * self.dtype.itemsize]
        return np.frombuffer(raw.tobytes(), dtype=self.dtype)

    def get(self, idx, offset=0, length=None):
        """Partial read (reference ``get``): ``length`` tokens from
        ``offset`` inside sample ``idx`` — the curriculum-seqlen hook."""
        full = self[idx]
        end = len(full) if length is None else offset + length
        return full[offset:end]

    @property
    def supports_prefetch(self):
        return False  # memmap pages on demand


def make_dataset(prefix, impl="mmap", **kw):
    """Reference ``make_dataset`` entry point (only the mmap impl survives —
    the others existed for pre-mmap torch versions)."""
    assert impl in ("mmap", "infer"), f"unsupported indexed_dataset impl {impl}"
    return MMapIndexedDataset(prefix)
