"""Network front end tests (``inference/serving/frontend/``,
``docs/serving.md`` "Network front end").

The acceptance contract: an asyncio HTTP server over a REAL
``ServingEngine`` serves >= 12 concurrent mixed requests (streaming +
blocking, 2 client_ids, 2 priorities) with greedy outputs
bitwise-identical to solo ``generate()`` and exactly ONE decode
executable minted for the server lifetime; a fairness overload only
sheds the heavy client; SIGTERM during active HTTP streaming ends every
stream with a typed PREEMPTED event, publishes a crash-atomic snapshot,
and a restarted server resumes the undrained requests bitwise with
fairness balances and priorities intact."""

import http.client
import json
import os
import signal
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.inference.serving.frontend import ServingHTTPFrontend
from deepspeed_tpu.inference.serving.frontend.fairness import \
    FairnessTracker
from deepspeed_tpu.inference.serving.slo import (QueueFull, RequestStatus,
                                                 TokenStream)
from deepspeed_tpu.models.transformer import Transformer, TransformerConfig


def tiny_cfg(**over):
    base = dict(vocab_size=97, hidden_size=64, num_layers=2, num_heads=4,
                max_seq_len=64, use_flash_attention=False, dtype="float32")
    base.update(over)
    return TransformerConfig(**base)


SERVING = {"enabled": True, "num_slots": 3, "max_cache_len": 64,
           "prefill_chunk": 8, "prefill_token_budget": 16,
           "decode_block": 2, "priority_lanes": 2}


def _build_engine(**serving_over):
    model = Transformer(tiny_cfg())
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 97, (2, 12)),
                      jnp.int32)
    params = model.init(jax.random.key(0), {"input_ids": ids})
    eng = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "prefill_chunk_size": 8,
                       "serving": {**SERVING, **serving_over}})
    eng.set_params(params)
    return eng


@pytest.fixture(scope="module")
def shared_engine():
    """One InferenceEngine for the module — each test opens its own
    ``eng.serve(...)`` server over it (close() retires only the
    ServingEngine)."""
    return _build_engine()


def _workload(rng, n, lo=9, hi=21, new_lo=3, new_hi=13):
    prompts = [rng.integers(1, 97, (int(p),)).astype(np.int32)
               for p in rng.integers(lo, hi, (n,))]
    news = [int(x) for x in rng.integers(new_lo, new_hi, (n,))]
    return prompts, news


def _solo(eng, prompt, n, eos=-1):
    return np.asarray(eng.generate(prompt[None], max_new_tokens=n,
                                   eos_token_id=eos))[0]


def _post(port, payload, timeout=180):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", "/v1/generate", json.dumps(payload))
    return conn, conn.getresponse()


def _read_stream(resp):
    """Consume an NDJSON chunked stream; returns (tokens, end_event,
    arrival_monotonics)."""
    toks, end, at = [], None, []
    while True:
        line = resp.readline()
        if not line:
            break
        ev = json.loads(line)
        if ev["event"] == "token":
            toks.append(ev["token"])
            at.append(time.monotonic())
        else:
            end = ev
            break
    return toks, end, at


# ---------------------------------------------------------------------- #
# Fairness tracker unit (injected clock — fully deterministic)
# ---------------------------------------------------------------------- #
def test_fairness_tracker_decay_budget_and_state():
    now = [0.0]
    tr = FairnessTracker(10.0, window_s=5.0, clock=lambda: now[0])
    assert tr.budget == 50.0
    assert tr.allow("a") and tr.usage("a") == 0.0
    tr.charge("a", 50.0)
    assert not tr.allow("a"), "at budget: deny"
    assert tr.allow("b"), "other tenants keep flowing"
    now[0] = 5.0                         # one window: decay by 1/e
    assert tr.usage("a") == pytest.approx(50.0 / np.e)
    assert tr.allow("a"), "decayed back under budget"
    # state round-trip: balances survive, server config wins
    tr.charge("b", 30.0)
    state = tr.state_dict()
    tr2 = FairnessTracker(10.0, window_s=5.0, clock=lambda: now[0])
    tr2.load_state(state)
    assert tr2.usage("b") == pytest.approx(30.0)
    # near-zero balances are dropped from the map (bounded tenant set)
    now[0] = 500.0
    assert tr.window_usage() == {}
    with pytest.raises(ValueError):
        FairnessTracker(0.0)


# ---------------------------------------------------------------------- #
# Engine-level satellites: unknown rids, streaming equivalence, priority
# ---------------------------------------------------------------------- #
def test_unknown_rid_raises_keyerror(shared_engine):
    srv = shared_engine.serve()
    rid = srv.submit(np.arange(1, 10, dtype=np.int32), max_new_tokens=3)
    for call in (srv.result, srv.cancel, srv.status, srv.token_events):
        with pytest.raises(KeyError, match="unknown request id"):
            call(rid + 999)
    assert srv.result(rid) is None, "known but still queued: None"
    srv.drain()
    assert srv.result(rid).status == RequestStatus.COMPLETED
    srv.close()


def test_token_stream_bitwise_with_eos_and_cancel(shared_engine):
    """Satellite: the token stream of a greedy request is bitwise the
    final RequestResult's generated tokens (ids AND order), including a
    mid-stream EOS retirement; a cancelled stream terminates with the
    typed CANCELLED event."""
    eng = shared_engine
    rng = np.random.default_rng(7)
    prompts, news = _workload(rng, 4)
    # make request 0 retire on a mid-stream EOS
    probe = _solo(eng, prompts[0], news[0])
    eos0 = int(probe[len(prompts[0]) + news[0] // 2])
    eoss = [eos0, -1, -1, -1]

    srv = eng.serve()
    rids = [srv.submit(p, max_new_tokens=n, eos_token_id=e)
            for p, n, e in zip(prompts, news, eoss)]
    streams = [srv.token_events(r) for r in rids]
    # cancel the last request once it is running (its stream must END)
    while srv.status(rids[3]) == RequestStatus.QUEUED:
        srv.step()
    srv.cancel(rids[3])
    srv.drain()

    for i in (0, 1, 2):
        toks, end = streams[i].tokens(timeout=5)
        res = srv.result(rids[i])
        P = len(prompts[i])
        want = [int(t) for t in res.output[P:]]
        # the result output is eos-padded to max_new past an early stop;
        # the stream carries exactly what the device emitted
        assert toks == want[:len(toks)] and len(toks) >= 1, (i, toks)
        assert end["status"] == RequestStatus.COMPLETED
        if i == 0:
            assert toks[-1] == eos0, "EOS itself is streamed last"
            # retirement at the FIRST greedy occurrence of the eos token
            # (the probe picked it from index news[0]//2, but greedy may
            # emit it earlier too) — and strictly mid-stream
            gen = [int(t) for t in probe[len(prompts[0]):]]
            assert len(toks) == gen.index(eos0) + 1 <= news[0], \
                (toks, gen)
        else:
            assert len(toks) == news[i], "full budget streamed"
        np.testing.assert_array_equal(
            res.output, _solo(eng, prompts[i], news[i], eoss[i]),
            err_msg=f"request {i} diverges from solo generate()")
    toks3, end3 = streams[3].tokens(timeout=5)
    assert end3["status"] == RequestStatus.CANCELLED, end3
    # late subscription replays the full stream identically
    replay, rend = srv.token_events(rids[1]).tokens(timeout=5)
    res1 = srv.result(rids[1])
    P1 = len(prompts[1])
    assert replay == [int(t) for t in res1.output[P1:P1 + len(replay)]]
    assert rend["status"] == RequestStatus.COMPLETED
    srv.close()


def test_priority_lanes_order_and_aging(shared_engine):
    """Lane 0 admits before lane 1 regardless of arrival order; with
    aging, a lane-1 request that has waited >= priority_aging_s reaches
    lane 0 and fcfs order takes over (no starvation)."""
    eng = shared_engine
    rng = np.random.default_rng(21)
    prompts, _ = _workload(rng, 4, lo=9, hi=12)

    srv = eng.serve(num_slots=1, priority_lanes=2, priority_aging_s=0.0)
    order = []
    rids = [srv.submit(prompts[0], max_new_tokens=3, priority=1),
            srv.submit(prompts[1], max_new_tokens=3, priority=1),
            srv.submit(prompts[2], max_new_tokens=3, priority=0)]
    pop = srv._pop_request                   # observe admission order
    srv._pop_request = lambda: order.append(pop()) or order[-1]
    srv.drain()
    assert [r.rid for r in order] == [rids[2], rids[0], rids[1]], \
        "lane 0 first, then fcfs within lane 1"
    with pytest.raises(ValueError, match="priority"):
        srv.submit(prompts[0], max_new_tokens=3, priority=2)
    srv.close()

    # aging: the lane-1 request has waited long enough to reach lane 0,
    # so a LATER lane-0 arrival no longer jumps it
    srv = eng.serve(num_slots=1, priority_lanes=2, priority_aging_s=0.05)
    order = []
    r_low = srv.submit(prompts[0], max_new_tokens=3, priority=1)
    time.sleep(0.12)                         # ages one lane
    r_hi = srv.submit(prompts[1], max_new_tokens=3, priority=0)
    pop = srv._pop_request
    srv._pop_request = lambda: order.append(pop()) or order[-1]
    srv.drain()
    assert [r.rid for r in order] == [r_low, r_hi], \
        "aged lane-1 request admits in fcfs order, not starved"
    srv.close()


def test_concurrent_submit_many_threads(shared_engine):
    """Thread-safety regression: many threads submit concurrently while
    a single scheduler-owner thread drives step(); every output is
    bitwise the solo run, and a second thread calling a driving method
    raises the owner error instead of racing the host mirror."""
    eng = shared_engine
    rng = np.random.default_rng(33)
    n_threads, per = 6, 3
    prompts, news = _workload(rng, n_threads * per)
    refs = [_solo(eng, p, n) for p, n in zip(prompts, news)]

    srv = eng.serve()
    rids = {}                            # (thread, i) -> rid
    errors = []

    def driver():
        try:
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                with srv._lock:
                    done = len(srv._results) >= n_threads * per
                if done:
                    return
                srv.step()
        except Exception as e:           # pragma: no cover - surfaced below
            errors.append(e)

    def submitter(t):
        try:
            for i in range(per):
                k = t * per + i
                rids[(t, i)] = srv.submit(prompts[k],
                                          max_new_tokens=news[k],
                                          client_id=f"tenant-{t % 2}")
                time.sleep(0.001)
        except Exception as e:           # pragma: no cover
            errors.append(e)

    drv = threading.Thread(target=driver, name="owner")
    drv.start()
    # bind the owner before asserting the non-owner refusal
    while srv._owner_thread is None:
        time.sleep(0.002)
    with pytest.raises(RuntimeError, match="scheduler owner"):
        srv.step()
    subs = [threading.Thread(target=submitter, args=(t,))
            for t in range(n_threads)]
    for s in subs:
        s.start()
    for s in subs:
        s.join(timeout=120)
    drv.join(timeout=150)
    assert not errors, errors
    assert len(rids) == n_threads * per
    for (t, i), rid in rids.items():
        k = t * per + i
        res = srv.result(rid)
        assert res is not None and res.status == RequestStatus.COMPLETED
        np.testing.assert_array_equal(
            res.output, refs[k],
            err_msg=f"thread {t} request {i} diverges under concurrency")
    srv.close()


# ---------------------------------------------------------------------- #
# HTTP end-to-end acceptance
# ---------------------------------------------------------------------- #
def test_http_end_to_end_mixed_concurrent():
    """>= 12 concurrent mixed requests over a real engine: streaming +
    blocking, 2 client_ids x 2 priorities; greedy outputs bitwise equal
    to solo generate(); exactly ONE decode executable for the server
    lifetime (the PR 5 zero-new-executables proof extended through the
    HTTP path).  Own engine: the executable count must not share an
    ``eng._aot`` with other tests' (garbage-collected) serving programs
    — a reused ``id()`` would alias their signatures."""
    eng = _build_engine()
    rng = np.random.default_rng(5)
    N = 14
    prompts, news = _workload(rng, N)
    refs = [_solo(eng, p, n) for p, n in zip(prompts, news)]

    srv = eng.serve()
    outs, errors = {}, []

    def client(k):
        try:
            stream = bool(k % 2)
            payload = {"input_ids": [int(t) for t in prompts[k]],
                       "max_new_tokens": news[k],
                       "client_id": f"tenant-{k % 2}",
                       "priority": (k // 2) % 2,
                       "stream": stream}
            conn, resp = _post(fe.port, payload)
            assert resp.status == 200, (k, resp.status, resp.read())
            if stream:
                toks, end, _ = _read_stream(resp)
                assert end["status"] == RequestStatus.COMPLETED, (k, end)
                outs[k] = ("stream", toks)
            else:
                body = json.loads(resp.read())
                assert body["status"] == RequestStatus.COMPLETED, (k, body)
                outs[k] = ("block", body["output"])
            conn.close()
        except Exception as e:           # pragma: no cover
            errors.append((k, e))

    with ServingHTTPFrontend(srv) as fe:
        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(N)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        # observability endpoints answer while the engine is live
        conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                          timeout=60)
        conn.request("GET", "/healthz")
        h = json.loads(conn.getresponse().read())
        assert h["ok"] and h["num_slots"] == srv.num_slots, h
        conn.request("GET", "/metrics")
        m = conn.getresponse().read().decode()
        assert "dstpu_serving_completed" in m
        conn.close()

    assert not errors, errors
    assert len(outs) == N
    for k in range(N):
        kind, got = outs[k]
        P = len(prompts[k])
        want = [int(t) for t in refs[k]]
        if kind == "stream":
            assert got == want[P:], \
                f"request {k} stream diverges from solo generate()"
        else:
            assert got == want, \
                f"request {k} blocking output diverges"
    # the one-decode-executable invariant holds through the HTTP path
    n_decode = sum(1 for sig in eng._aot
                   if sig and sig[0] == id(srv._decode_fn))
    assert n_decode == 1, n_decode
    srv.close()


def test_http_fairness_overload_sheds_only_heavy_client(shared_engine):
    """Fairness proof: the heavy client drives 4x the light client's
    load (4 connections x 4 sequential requests vs 4 single requests)
    against a budget one heavy ROUND blows through but a single light
    request cannot — only the heavy client is 429'd, every light request
    completes, and the light client's p99 TTFT stays bounded."""
    eng = shared_engine
    rng = np.random.default_rng(9)
    heavy_p, heavy_n = _workload(rng, 16, new_lo=6, new_hi=12)
    light_p, light_n = _workload(rng, 4, new_lo=3, new_hi=6)
    # budget 1.5 * 30 = 45 window tokens: a light request charges at
    # most ~26 (prompt <= 20 + 6 generated) — never over alone; the
    # first heavy round's 4 requests charge >= 60 — round 2 is 429'd.
    # The slow window (30 s >> test duration) keeps decay from
    # laundering the heavy client back under budget mid-test.
    srv = eng.serve(fairness_tokens_per_s=1.5, fairness_window_s=30.0)
    stats = {"heavy_429": 0, "heavy_ok": 0}
    light_results, errors = [], []
    lock = threading.Lock()

    def heavy(conn_idx):
        try:
            for k in range(conn_idx * 4, conn_idx * 4 + 4):
                conn, resp = _post(fe.port, {
                    "input_ids": [int(t) for t in heavy_p[k]],
                    "max_new_tokens": heavy_n[k], "client_id": "heavy"})
                body = json.loads(resp.read())
                with lock:
                    if resp.status == 429:
                        assert "fairness budget" in body["error"], body
                        stats["heavy_429"] += 1
                    else:
                        assert resp.status == 200, (resp.status, body)
                        stats["heavy_ok"] += 1
                conn.close()
        except Exception as e:           # pragma: no cover
            errors.append(("heavy", conn_idx, e))

    def light(k):
        try:
            conn, resp = _post(fe.port, {
                "input_ids": [int(t) for t in light_p[k]],
                "max_new_tokens": light_n[k], "client_id": "light"})
            body = json.loads(resp.read())
            light_results.append((resp.status, body))
            conn.close()
        except Exception as e:           # pragma: no cover
            errors.append(("light", k, e))

    with ServingHTTPFrontend(srv) as fe:
        threads = [threading.Thread(target=heavy, args=(c,))
                   for c in range(4)]
        threads += [threading.Thread(target=light, args=(k,))
                    for k in range(len(light_p))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)

    assert not errors, errors
    assert stats["heavy_429"] >= 1, \
        f"heavy client never hit its quota: {stats}, " \
        f"fairness_rejected={srv.stats['fairness_rejected']}"
    codes = [c for c, _ in light_results]
    assert codes == [200] * len(light_p), \
        f"light client was shed: {light_results}"
    for _, body in light_results:
        assert body["status"] == RequestStatus.COMPLETED, body
    ttfts = sorted(body["ttft_s"] for _, body in light_results)
    p99 = ttfts[min(len(ttfts) - 1, int(0.99 * len(ttfts)))]
    assert p99 < 60.0, f"light client's p99 TTFT unbounded: {ttfts}"
    assert srv.stats["fairness_rejected"] == stats["heavy_429"]
    srv.close()


def test_http_sigterm_streaming_preempt_restore_bitwise(tmp_path):
    """SIGTERM during active HTTP streaming: in-flight streams end with
    the typed PREEMPTED event, a crash-atomic snapshot is published, and
    a restarted server resumes the undrained requests BITWISE — with
    fairness balances and priorities intact."""
    snap = str(tmp_path / "snap")
    eng = _build_engine(fairness_tokens_per_s=10000.0,
                        fairness_window_s=60.0)
    rng = np.random.default_rng(13)
    prompts, _ = _workload(rng, 3, lo=10, hi=14)
    news = [40, 40, 38]                  # long decodes: SIGTERM lands mid-flight
    refs = [_solo(eng, p, n) for p, n in zip(prompts, news)]

    # drain_budget_s=0: snapshot immediately on SIGTERM — the tiny model
    # would otherwise finish all 40-token budgets inside a real drain
    # window and leave nothing to prove resume with
    srv = eng.serve(num_slots=2, fairness_tokens_per_s=10000.0,
                    fairness_window_s=60.0, drain_budget_s=0.0)
    got = {}
    errors = []

    def streamer(k):
        try:
            conn, resp = _post(fe.port, {
                "input_ids": [int(t) for t in prompts[k]],
                "max_new_tokens": news[k],
                "client_id": f"tenant-{k % 2}", "priority": k % 2,
                "stream": True})
            assert resp.status == 200, resp.status
            got[k] = _read_stream(resp)
            conn.close()
        except Exception as e:           # pragma: no cover
            errors.append((k, e))

    fe = ServingHTTPFrontend(srv, snapshot_dir=snap).start()
    fe.install_signal_handlers()
    try:
        threads = [threading.Thread(target=streamer, args=(k,))
                   for k in range(3)]
        for t in threads:
            t.start()
        # wait until every stream is producing, then SIGTERM ourselves
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            with srv._lock:
                flowing = sum(1 for r in srv._requests.values()
                              if 1 <= len(r.tokens) < r.max_new - 25)
            if flowing >= 2:
                break
            time.sleep(0.01)
        os.kill(os.getpid(), signal.SIGTERM)
        tag, snapped, _finished = fe.join_preempted(timeout=120)
        for t in threads:
            t.join(timeout=60)
    finally:
        fe.shutdown()
    assert not errors, errors
    assert tag is not None and len(snapped) >= 2, (tag, snapped)
    for k in range(3):
        toks, end, _ = _read_stream_result(got, k)
        assert end is not None, f"stream {k} ended with no typed event"
        assert end["status"] in (RequestStatus.PREEMPTED,
                                 RequestStatus.COMPLETED), end
        if end["status"] == RequestStatus.PREEMPTED:
            assert "resume" in end["detail"], end

    # ---- restarted server: restore and finish bitwise ----
    eng2 = _build_engine(fairness_tokens_per_s=10000.0,
                         fairness_window_s=60.0)
    srv2 = eng2.serve(num_slots=2, fairness_tokens_per_s=10000.0,
                      fairness_window_s=60.0)
    rids = srv2.restore(snap)
    assert sorted(rids) == sorted(snapped)
    # correlate each restored rid back to its workload index by prompt
    # (the 3 streamer threads raced submit(), so rid order is arbitrary)
    def _k_of(req):
        ks = [k for k in range(3)
              if np.array_equal(req.ids, prompts[k])]
        assert len(ks) == 1, "ambiguous prompt correlation"
        return ks[0]

    # priorities and fairness balances survived the snapshot
    for rid in rids:
        req = srv2._requests[rid]
        assert req.priority == _k_of(req) % 2, (rid, req.priority)
    usage = srv2._fairness.window_usage()
    assert usage and all(v > 0 for v in usage.values()), \
        f"fairness balances lost across preempt/restore: {usage}"
    # freeze the fairness clock: with decay pinned, the post-drain
    # balance must be EXACTLY snapshot balance + newly generated tokens.
    # Re-admission double-charging the re-prefilled prompt+prefix (the
    # server's preemption cost, not the client's) would overshoot.
    frozen = srv2._fairness._clock()
    srv2._fairness._clock = lambda: frozen
    usage = srv2._fairness.window_usage()    # re-read at the frozen instant
    k_by_rid = {rid: _k_of(srv2._requests[rid]) for rid in rids}
    outs = srv2.drain()
    for rid in rids:
        np.testing.assert_array_equal(
            outs[rid], refs[k_by_rid[rid]],
            err_msg=f"resumed request {rid} diverges from the "
                    f"uninterrupted solo run")
    post = srv2._fairness.window_usage()
    for key in post:
        new_toks = sum(
            len(srv2._requests[rid].tokens)
            - len(srv2._requests[rid].prefix)
            for rid in rids
            if FairnessTracker.key(srv2._requests[rid].client_id) == key)
        assert post[key] == pytest.approx(usage.get(key, 0.0) + new_toks), \
            f"client {key}: restore double-charged the re-prefill " \
            f"({usage.get(key, 0.0)} + {new_toks} new != {post[key]})"
    srv2.close()


def _read_stream_result(got, k):
    """(tokens, end, arrivals) for streamer k, tolerating a thread that
    recorded nothing (it would have pushed an error instead)."""
    return got.get(k, ([], None, []))


# ---------------------------------------------------------------------- #
# Post-review hardening regressions
# ---------------------------------------------------------------------- #
def test_token_stream_dead_subscriber_does_not_break_producer():
    """A subscriber whose bridge raises (e.g. call_soon_threadsafe into
    an asyncio loop that closed mid-shutdown) must never break the
    producer — close()/step() push terminal events under the engine
    lock.  The bridge is dropped; the queue stays readable."""
    calls = []

    def bad(ev):
        calls.append(ev)
        raise RuntimeError("Event loop is closed")

    st = TokenStream(7, on_event=bad)
    st.push({"event": "token", "rid": 7, "index": 0, "token": 3})
    st.push({"event": "end", "rid": 7, "status": "COMPLETED",
             "detail": ""})
    assert len(calls) == 1, "bridge must be dropped after its first raise"
    assert st.get(timeout=1)["token"] == 3
    assert st.get(timeout=1)["event"] == "end"


def test_http_malformed_head_gets_400_then_drop(shared_engine):
    """A head the server cannot frame (bad request line, junk
    Content-Length) answers 400 and drops the connection — never a
    silent close, never an unhandled handler crash."""
    import socket
    srv = shared_engine.serve()
    with ServingHTTPFrontend(srv) as fe:
        for head in (b"POST /v1/generate HTTP/1.1\r\n"
                     b"Content-Length: abc\r\n\r\n",
                     b"GARBAGE\r\n\r\n",
                     b"POST /v1/generate HTTP/1.1\r\n"
                     b"Content-Length: -5\r\n\r\n"):
            s = socket.create_connection(("127.0.0.1", fe.port),
                                         timeout=30)
            s.sendall(head)
            data = s.recv(4096)
            assert data.startswith(b"HTTP/1.1 400"), (head, data)
            assert s.recv(4096) == b"", "connection must drop after an " \
                                        "unframeable head"
            s.close()
        # the server still serves real requests afterwards
        conn, resp = _post(fe.port, {"input_ids": [1, 2, 3],
                                     "max_new_tokens": 2})
        assert resp.status == 200
        conn.close()
    srv.close()


def test_http_start_failure_releases_engine(shared_engine):
    """start() failing after the scheduler thread claimed the engine
    (port already bound) must unwind the claim: a retry frontend on a
    free port serves the SAME engine instead of finding it owner-bound
    to a dead thread."""
    srv_a = shared_engine.serve()
    srv_b = shared_engine.serve()
    fe_a = ServingHTTPFrontend(srv_a).start()
    try:
        with pytest.raises(OSError):
            ServingHTTPFrontend(srv_b, port=fe_a.port).start()
        with ServingHTTPFrontend(srv_b) as fe_b:
            conn, resp = _post(fe_b.port, {"input_ids": [1, 2, 3],
                                           "max_new_tokens": 2})
            assert resp.status == 200
            body = json.loads(resp.read())
            assert body["status"] == RequestStatus.COMPLETED
            conn.close()
    finally:
        fe_a.shutdown()
        srv_a.close()
        srv_b.close()
