"""Model-family tests: shapes, loss finiteness, training integration with the
engine at ZeRO-3 + TP sharding rules."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.transformer import (Transformer, TransformerConfig,
                                              cross_entropy_loss,
                                              reference_attention)
from deepspeed_tpu.models.opt import opt_model, opt_config, llama_model


def tiny_config(**over):
    base = dict(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                max_seq_len=64, use_flash_attention=False, dtype="float32")
    base.update(over)
    return TransformerConfig(**base)


def lm_batch(bs=4, seq=16, vocab=128, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, vocab, (bs, seq)).astype(np.int32)
    return {"input_ids": ids}


def test_forward_loss_finite():
    model = Transformer(tiny_config())
    params = model.init(jax.random.key(0), lm_batch())
    loss = model.apply(params, lm_batch())
    assert np.isfinite(float(loss))
    assert float(loss) == pytest.approx(np.log(128), rel=0.3)  # ~uniform at init


def test_logits_shape():
    cfg = tiny_config()
    model = Transformer(cfg)
    batch = lm_batch()
    params = model.init(jax.random.key(0), batch)
    logits = model.apply(params, batch["input_ids"], method=Transformer.logits)
    assert logits.shape == (4, 16, 128)


def test_llama_variant_forward():
    model = Transformer(tiny_config(rms_norm=True, gated_mlp=True,
                                    activation="silu", position_embedding="rope",
                                    num_kv_heads=2, tie_word_embeddings=False))
    batch = lm_batch()
    params = model.init(jax.random.key(0), batch)
    loss = model.apply(params, batch)
    assert np.isfinite(float(loss))


def test_param_count_matches_analytic():
    cfg = tiny_config(tie_word_embeddings=True)
    model = Transformer(cfg)
    params = model.init(jax.random.key(0), lm_batch())
    actual = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params))
    # analytic count ignores small bias terms; require within 2%
    assert abs(actual - cfg.num_params()) / actual < 0.02


def test_opt_preset_sizes():
    cfg = opt_config("opt-1.3b")
    n = cfg.num_params()
    assert 1.2e9 < n < 1.5e9, f"opt-1.3b param count off: {n/1e9:.2f}B"


def test_cross_entropy_ignore_index():
    logits = jnp.zeros((2, 3, 10))
    labels = jnp.array([[1, -100, 2], [-100, -100, 3]])
    loss = cross_entropy_loss(logits, labels)
    assert float(loss) == pytest.approx(np.log(10), rel=1e-5)


def test_gqa_reference_attention():
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((2, 8, 4, 16)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((2, 8, 2, 16)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((2, 8, 2, 16)).astype(np.float32))
    out = reference_attention(q, k, v, causal=True)
    assert out.shape == (2, 8, 4, 16)
    # causality: output at position 0 must not depend on later keys
    k2 = k.at[:, 5:].set(0.0)
    v2 = v.at[:, 5:].set(0.0)
    out2 = reference_attention(q, k2, v2, causal=True)
    np.testing.assert_allclose(out[:, :5], out2[:, :5], rtol=1e-5)


def test_transformer_with_engine_zero3():
    model = Transformer(tiny_config())
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3}})
    losses = []
    for i in range(6):
        batch = lm_batch(bs=8, seed=0)  # fixed batch: must memorize
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0]


def test_transformer_with_tp():
    model = Transformer(tiny_config())
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 2,
                "tensor_parallel": {"tp_size": 2},
                "zero_optimization": {"stage": 1},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    assert engine.topology.tp == 2 and engine.topology.dp == 4
    batch = lm_batch(bs=8)
    loss = engine(batch)
    engine.backward(loss)
    engine.step()
    # verify at least one kernel actually sharded over tp
    from jax.sharding import PartitionSpec as P
    leaves = jax.tree_util.tree_leaves_with_path(engine.params)
    tp_sharded = [p for p, l in leaves
                  if any("tp" in str(e) for e in l.sharding.spec if e is not None)]
    assert tp_sharded, "no parameter sharded over tp axis"


def test_fused_qkv_trains_and_infers():
    """fused_qkv: one QKV gemm; loss finite, decode path works, params carry
    a single qkv_proj kernel in place of the three separate projections."""
    cfg = tiny_config(fused_qkv=True)
    model = Transformer(cfg)
    params = model.init(jax.random.key(0), lm_batch())
    flat = jax.tree_util.tree_leaves_with_path(params)
    names = {"/".join(str(getattr(p, "key", p)) for p in path)
             for path, _ in flat}
    assert any("qkv_proj" in n for n in names)
    assert not any("q_proj" in n for n in names)
    loss = model.apply(params, lm_batch())
    assert np.isfinite(float(loss))
    # decode with KV cache still works
    cache = model.init_cache(2, 16)
    ids = lm_batch(bs=2, seq=4)["input_ids"]
    logits, cache = model.apply(params, ids, cache, 0,
                                method=Transformer.decode)
    assert logits.shape == (2, 4, 128)
    g = jax.grad(lambda p: model.apply(p, lm_batch()))(params)
    assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))


@pytest.mark.slow
def test_chunked_cross_entropy_matches_full():
    """loss_seq_chunks must reproduce the full-logits loss exactly (same
    nll-sum / valid-count composition), values and gradients."""
    cfg_full = tiny_config()
    cfg_chunk = tiny_config(loss_seq_chunks=4)
    model_full = Transformer(cfg_full)
    model_chunk = Transformer(cfg_chunk)
    batch = lm_batch(bs=2, seq=16)
    params = model_full.init(jax.random.key(0), batch)
    lf = float(model_full.apply(params, batch))
    lc = float(model_chunk.apply(params, batch))
    assert lc == pytest.approx(lf, rel=1e-5)
    # with an attention mask (ignore_index positions)
    mask = np.ones((2, 16), np.int32)
    mask[:, 10:] = 0
    mb = dict(batch, attention_mask=mask)
    assert float(model_chunk.apply(params, mb)) == \
        pytest.approx(float(model_full.apply(params, mb)), rel=1e-5)
    gf = jax.grad(lambda p: model_full.apply(p, batch))(params)
    gc = jax.grad(lambda p: model_chunk.apply(p, batch))(params)
    for a, b in zip(jax.tree.leaves(gf), jax.tree.leaves(gc)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_chunked_loss_untied_head_matches_full():
    """loss_seq_chunks with an untied lm_head must trace (pure-closure head
    inside jax.checkpoint/lax.map) and match the full-logits loss."""
    from deepspeed_tpu.models.transformer import Transformer, TransformerConfig
    import jax, numpy as np, jax.numpy as jnp
    kw = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
              max_seq_len=16, dtype="float32", use_flash_attention=False,
              remat=False, tie_word_embeddings=False)
    m_full = Transformer(TransformerConfig(**kw))
    m_chunk = Transformer(TransformerConfig(**kw, loss_seq_chunks=4))
    ids = np.random.default_rng(0).integers(0, 64, (2, 16)).astype(np.int32)
    params = jax.jit(m_full.init)(jax.random.key(0), {"input_ids": ids})
    l_full = float(m_full.apply(params, {"input_ids": ids}))
    l_chunk = float(m_chunk.apply(params, {"input_ids": ids}))
    np.testing.assert_allclose(l_chunk, l_full, rtol=1e-5)


def test_chunked_loss_unrolled_matches(monkeypatch):
    """The unrolled chunk-loop escape hatch must be numerically identical
    to the lax.map path."""
    from deepspeed_tpu.models.transformer import chunked_cross_entropy_loss
    import jax, numpy as np, jax.numpy as jnp
    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.standard_normal((2, 16, 8)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, (2, 16)), jnp.int32)
    W = jnp.asarray(rng.standard_normal((8, 10)), jnp.float32)
    head = lambda x: x @ W
    monkeypatch.setenv("DSTPU_LOSS_CHUNK_UNROLL", "0")
    a = float(chunked_cross_entropy_loss(h, labels, head, 4))
    monkeypatch.setenv("DSTPU_LOSS_CHUNK_UNROLL", "1")
    b = float(chunked_cross_entropy_loss(h, labels, head, 4))
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_chunked_loss_untied_projected_head():
    """embed_proj_dim + untied lm_head + chunked loss: the pure-closure head
    must init lm_head at project_out width (regression for the _head_pure
    width mismatch)."""
    from deepspeed_tpu.models.transformer import Transformer, TransformerConfig
    import jax, numpy as np
    kw = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
              max_seq_len=16, dtype="float32", use_flash_attention=False,
              remat=False, tie_word_embeddings=False, embed_proj_dim=16)
    ids = np.random.default_rng(0).integers(0, 64, (2, 16)).astype(np.int32)
    m_full = Transformer(TransformerConfig(**kw))
    params = jax.jit(m_full.init)(jax.random.key(0), {"input_ids": ids})
    m_chunk = Transformer(TransformerConfig(**kw, loss_seq_chunks=4))
    l_full = float(m_full.apply(params, {"input_ids": ids}))
    l_chunk = float(m_chunk.apply(params, {"input_ids": ids}))
    np.testing.assert_allclose(l_chunk, l_full, rtol=1e-5)
