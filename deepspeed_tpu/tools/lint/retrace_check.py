"""Runtime retrace counter for the registered serving hot paths.

The static TL006 rule catches jit-signature instability it can SEE in
source; this harness catches the drift it can't: build a real serving
engine, dispatch its programs across several rounds of DRIFTING host
bookkeeping (different prompts, prompt lengths, request ids, client ids,
deadlines, submit order — everything the host is allowed to vary), and
count what actually compiled.  The contract: the serving decode / admit /
admission-prefill programs each compile EXACTLY ONCE per server lifetime,
no matter how the host-side bookkeeping moves — one new abstract signature
anywhere in the dispatch path (a weak-typed scalar that used to be an
array, a shape that started drifting with queue depth) shows up here as a
second signature before it ships as a 30 s mid-serve recompile.

Counting: every serving dispatch routes through
``InferenceEngine._run_guarded``, which AOT-compiles once per
``(program, abstract-signature)`` and memoizes in ``engine._aot`` — so the
number of ``_aot`` signatures per program IS the compile count.  The jit
fast path's specialization cache (``fn._cache_size()``) is asserted too
when jax exposes it.

Runs on CPU at toy sizes in tier-1 (``tests/unit/test_tpu_lint.py``);
``measure_serving_retraces`` is importable for ad-hoc use.
"""

import numpy as np


def _tiny_served_engine(seed=0):
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import (Transformer,
                                                  TransformerConfig)
    cfg = TransformerConfig(vocab_size=97, hidden_size=32, num_layers=2,
                            num_heads=4, max_seq_len=64,
                            use_flash_attention=False, dtype="float32")
    model = Transformer(cfg)
    ids = jnp.asarray(np.random.default_rng(seed).integers(0, 97, (2, 12)),
                      jnp.int32)
    params = model.init(jax.random.key(0), {"input_ids": ids})
    eng = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "prefill_chunk_size": 8,
                       "serving": {"enabled": True, "num_slots": 2,
                                   "max_cache_len": 48, "prefill_chunk": 8,
                                   "prefill_token_budget": 16,
                                   "decode_block": 2}})
    eng.set_params(params)
    return eng


def _signature_counts(srv):
    """{program: number of distinct AOT signatures compiled} — the
    compile count per serving program (see module docstring)."""
    eng = srv.engine
    out = {}
    for label, fn in (("decode", srv._decode_fn), ("admit", srv._admit_fn),
                      ("chunk", srv._chunk_fn)):
        n = sum(1 for sig in eng._aot if sig and sig[0] == id(fn))
        cache_size = getattr(fn, "_cache_size", lambda: None)()
        if cache_size:                    # jit fast-path specializations
            n = max(n, cache_size)
        out[label] = n
    return out


def measure_serving_retraces(rounds=3, seed=0):
    """Run ``rounds`` serving rounds with drifting host bookkeeping and
    return ``{"per_round": [counts...], "final": counts}`` where each
    ``counts`` maps program -> compile count so far.  The invariant under
    test: every count stays at 1 from round 1 on."""
    rng = np.random.default_rng(seed)
    eng = _tiny_served_engine(seed)
    srv = eng.serve()
    per_round = []
    for r in range(rounds):
        # drifting host bookkeeping: round-varying request count, prompt
        # lengths/contents, completion lengths, eos ids, client ids,
        # deadlines, submit order — none of it may reach a traced shape
        n = 3 + (r % 2)
        lens = rng.integers(9, 21, (n,))
        news = rng.integers(3, 9, (n,))
        for i in range(n):
            prompt = rng.integers(1, 97, (int(lens[i]),)).astype(np.int32)
            srv.submit(prompt, max_new_tokens=int(news[i]),
                       eos_token_id=-1 if i % 2 else 96,
                       client_id=f"round{r}-client{i}",
                       deadline_s=None if i % 2 else 600.0 + r)
        srv.drain()
        per_round.append(_signature_counts(srv))
    return {"per_round": per_round, "final": per_round[-1]}


def main():
    result = measure_serving_retraces()
    ok = True
    for r, counts in enumerate(result["per_round"], 1):
        line = ", ".join(f"{k}={v}" for k, v in counts.items())
        print(f"[retrace] round {r}: {line}")
        ok = ok and all(v == 1 for v in counts.values())
    verdict = ("OK — every serving program compiled exactly once" if ok
               else "RETRACE DRIFT — a serving program compiled more than "
                    "once (or never)")
    print(f"[retrace] {verdict}")
    return 0 if ok else 1


if __name__ == "__main__":
    import sys
    sys.exit(main())
