"""deepspeed_tpu — a TPU-native distributed training & inference framework
with the capability surface of DeepSpeed v0.9.3 (reference
``deepspeed/__init__.py``), re-designed for JAX/XLA/Pallas/pjit.

Top-level API parity:

* ``initialize()``          (reference ``__init__.py:58``)
* ``init_inference()``      (reference ``__init__.py:260``)
* ``init_distributed``      (re-export, reference ``__init__.py:32``)
* ``add_config_arguments()``(reference ``__init__.py:237``)
"""

__version__ = "0.1.0"
__git_hash__ = None
__git_branch__ = None

import os as _os

import jax as _jax

# Sharding-invariant RNG: without this, jax<0.5's non-partitionable threefry
# lets the SPMD partitioner produce layout-DEPENDENT random values, so the
# same seed inits different weights under different ZeRO/MiCS topologies
# (and costs an all-gather of the bits on TPU).  This DOES change
# jax.random streams for the same seed; the only opt-out is the env var
# JAX_THREEFRY_PARTITIONABLE (=0 to keep legacy streams) — an explicit
# pre-import config update to False is indistinguishable from the default
# and gets flipped.
if "JAX_THREEFRY_PARTITIONABLE" not in _os.environ and \
        not _jax.config.jax_threefry_partitionable:
    _jax.config.update("jax_threefry_partitionable", True)

from deepspeed_tpu.accelerator import get_accelerator, set_accelerator  # noqa: F401
from deepspeed_tpu import comm  # noqa: F401
from deepspeed_tpu.comm import init_distributed  # noqa: F401
from deepspeed_tpu.parallel import topology  # noqa: F401
from deepspeed_tpu.parallel.topology import ParallelTopology, initialize_topology  # noqa: F401
from deepspeed_tpu.runtime.config import DeepSpeedConfig  # noqa: F401
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime import zero  # noqa: F401
from deepspeed_tpu.runtime.pipe.module import PipelineModule, LayerSpec, TiedLayerSpec  # noqa: F401
from deepspeed_tpu.ops.transformer.transformer import (  # noqa: F401
    DeepSpeedTransformerConfig, DeepSpeedTransformerLayer)
from deepspeed_tpu.runtime.activation_checkpointing import checkpointing  # noqa: F401
from deepspeed_tpu.utils.logging import logger, log_dist  # noqa: F401

from deepspeed_tpu.ops.adam.fused_adam import FusedAdam, FusedAdamW  # noqa: F401
from deepspeed_tpu.ops.lamb.fused_lamb import FusedLamb  # noqa: F401


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               mpu=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               config_params=None,
               loss_fn=None,
               topology=None):
    """Initialize the engine (reference ``deepspeed/__init__.py:58``).

    Returns the tuple ``(engine, optimizer, training_dataloader, lr_scheduler)``.
    ``model`` is a flax Module or ``apply_fn(params, batch) -> loss``;
    ``model_parameters`` an optional initial parameter pytree (else params are
    lazily initialized *sharded* at first forward).  The engine choice
    (plain vs pipeline) mirrors reference ``__init__.py:150-190``.
    """
    if config is None and config_params is not None:
        config = config_params
    if config is None and args is not None and hasattr(args, "deepspeed_config"):
        config = args.deepspeed_config
    assert config is not None, "DeepSpeed requires --deepspeed_config or config="

    if topology is None and mpu is not None:
        # honor an external Megatron-style mpu (reference __init__.py:88:
        # the engine adopts mpu's groups) by building the mesh from its
        # parallel degrees
        from deepspeed_tpu.parallel import topology as _topo

        def _mpu_size(*names):
            for n in names:
                fn = getattr(mpu, n, None)
                if callable(fn):
                    return fn()
            return 1

        # probe both naming schemes: legacy Megatron (model_parallel) and
        # Megatron-Core (tensor_model_parallel / pipeline_model_parallel)
        tp_size = _mpu_size("get_model_parallel_world_size",
                            "get_tensor_model_parallel_world_size")
        pp_size = _mpu_size("get_pipe_parallel_world_size",
                            "get_pipeline_model_parallel_world_size")
        topology = _topo.initialize_topology(tp=tp_size, pp=pp_size)

    from deepspeed_tpu.runtime.pipe.module import PipelineModule
    if isinstance(model, PipelineModule):
        from deepspeed_tpu.runtime.pipe.engine import PipelineEngine
        engine = PipelineEngine(model=model,
                                optimizer=optimizer,
                                model_parameters=model_parameters,
                                training_data=training_data,
                                lr_scheduler=lr_scheduler,
                                collate_fn=collate_fn,
                                config=config,
                                topology=topology)
    else:
        # Hybrid engine for RLHF rollout+train (reference __init__.py:150-190
        # chooses DeepSpeedHybridEngine on config.hybrid_engine.enabled)
        cfg_dict = config
        if isinstance(config, str):
            import json
            with open(config) as f:
                cfg_dict = json.load(f)
        hybrid = isinstance(cfg_dict, dict) and \
            cfg_dict.get("hybrid_engine", {}).get("enabled", False)
        engine_cls = DeepSpeedEngine
        if hybrid:
            from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine
            engine_cls = DeepSpeedHybridEngine
        engine = engine_cls(model=model,
                            optimizer=optimizer,
                            model_parameters=model_parameters,
                            training_data=training_data,
                            lr_scheduler=lr_scheduler,
                            collate_fn=collate_fn,
                            config=cfg_dict,
                            loss_fn=loss_fn,
                            topology=topology)
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def init_inference(model=None, config=None, **kwargs):
    """Initialize the inference engine (reference ``__init__.py:260``).

    ``model`` may be a flax Module, an HF torch model, or an HF model
    name/path — torch models are converted through the injection policies
    (``module_inject/``), the analog of the reference's kernel injection."""
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    if isinstance(config, dict):
        config = DeepSpeedInferenceConfig(**config, **kwargs)
    elif config is None:
        config = DeepSpeedInferenceConfig(**kwargs)

    params = None
    is_torch = False
    if isinstance(model, str):
        is_torch = True
    else:
        try:
            import torch
            is_torch = isinstance(model, torch.nn.Module)
        except ImportError:
            pass
    if is_torch:
        from deepspeed_tpu.module_inject import convert_hf_model
        from deepspeed_tpu.inference.config import normalize_dtype_str
        model, params = convert_hf_model(
            model, dtype=normalize_dtype_str(config.dtype))
    if config.quant.kv_cache:
        # int8 KV cache: flip the model-config knob (decoder families);
        # warn instead of failing for models without a KV cache
        cfg = getattr(model, "config", None)
        if hasattr(cfg, "kv_cache_quant"):
            if not cfg.kv_cache_quant:
                import dataclasses
                model = model.clone(
                    config=dataclasses.replace(cfg, kv_cache_quant=True))
        else:
            from deepspeed_tpu.utils.logging import warning_once
            warning_once(f"quant.kv_cache: {type(model).__name__} has no "
                         "kv_cache_quant knob — ignored")
    return InferenceEngine(model, config, params=params)


def add_config_arguments(parser):
    """Add --deepspeed / --deepspeed_config CLI args (reference
    ``__init__.py:237``)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag to launcher)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to DeepSpeed json configuration")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help="Deprecated alias of --deepspeed")
    group.add_argument("--local_rank", type=int, default=-1,
                       help="local rank passed by the launcher")
    return parser
