"""Spatial (diffusers) fused bias-add ops — reference
``csrc/spatial/csrc/opt_bias_add.cu`` behind ``SpatialInferenceBuilder``:
``nhwc_bias_add``, ``bias_add_add``, ``bias_add_bias_add`` for UNet/VAE
residual paths.

On TPU these are single XLA fusions — the value of keeping named ops is API
parity for injected modules, plus guaranteed NHWC channel-last broadcasting
(the reference kernels exist because torch's NCHW layout made the adds
memory-hostile; TPU convs are NHWC-native)."""

import jax


@jax.jit
def nhwc_bias_add(activation, bias):
    """out = act + bias (bias broadcast over the channel-last dim)."""
    return activation + bias.reshape((1,) * (activation.ndim - 1) + (-1,))


@jax.jit
def nhwc_bias_add_add(activation, bias, other):
    """out = (act + bias) + other (residual add, reference bias_add_add)."""
    return activation + bias.reshape((1,) * (activation.ndim - 1) + (-1,)) + other


@jax.jit
def nhwc_bias_add_bias_add(activation, bias, other, other_bias):
    """out = (act + bias) + (other + other_bias) (reference
    bias_add_bias_add — two biased tensors summed)."""
    shape = (1,) * (activation.ndim - 1) + (-1,)
    return (activation + bias.reshape(shape)
            + other + other_bias.reshape(shape))
