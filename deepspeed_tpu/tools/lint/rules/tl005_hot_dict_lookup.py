"""TL005 — per-step config/dict lookups on a hot path.

``config["..."]``/``cfg.get("...")`` inside a hot function re-does a string
hash + dict probe (and defeats any caching keyed on the extracted value)
once per step.  Hoist the read to setup time and close over the value; XLA
then bakes it into the compiled program.
"""

import ast

from deepspeed_tpu.tools.lint.core import Finding, dotted_name, rule

_CONFIG_TOKENS = ("config", "cfg", "settings", "hparams")


def _is_config_name(node):
    name = dotted_name(node)
    if not name:
        return False
    last = name.split(".")[-1].lower()
    return any(tok in last for tok in _CONFIG_TOKENS)


@rule("TL005", "per-step config lookup on a hot path")
def check(module):
    hot = module.hot_functions()
    if not hot:
        return
    seen = set()
    for fn in hot:
        for node in ast.walk(fn.node):
            if id(node) in seen:
                continue
            seen.add(id(node))
            target = None
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.slice, ast.Constant) and \
                    isinstance(node.slice.value, str) and \
                    _is_config_name(node.value):
                target = f'{dotted_name(node.value)}["{node.slice.value}"]'
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "get" and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str) and \
                    _is_config_name(node.func.value):
                target = (f'{dotted_name(node.func.value)}'
                          f'.get("{node.args[0].value}")')
            if target:
                yield Finding(
                    "TL005", module.path, node.lineno, node.col_offset,
                    f"{target} inside hot path '{fn.hot_name or fn.name}' — "
                    f"hoist the lookup to setup time and close over the "
                    f"value")
