"""TL010 negative fixture — sharded and deliberately-scalar placements
that must NOT be flagged: full specs, P() on scalars, pallas in_specs
(BlockSpecs, not shardings), and sharded placements of batch arrays."""
import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

mesh = Mesh(jax.devices(), ("tp",))


def body(x, w):
    return x @ w


# fully specced: batch sharded, weights tp-sharded
smap_ok = shard_map(body, mesh=mesh,
                    in_specs=(P("tp"), P(None, "tp")),
                    out_specs=P("tp"))


# P() on SCALAR control inputs is the correct spec, not replication debt
@functools.partial(shard_map, mesh=mesh,
                   in_specs=(P("tp"), P(), P()), out_specs=P("tp"))
def stepper(x, lr, step):
    return x * lr + step


def pallas_like(kernel, block):
    # pallas_call's in_specs are BlockSpecs — no mesh, not a sharding
    return pallas_call(kernel, in_specs=[block, block], out_specs=block)


def run_under_mesh(batch):
    with mesh:
        # shardings declared: inputs follow the committed layout
        step = jax.jit(lambda b: b * 2, out_shardings=NamedSharding(
            mesh, P("tp")))
        return step(batch)


def place(input_ids, scale):
    # batch array sharded; the scalar config value replicates by design
    ids = jax.device_put(input_ids, NamedSharding(mesh, P("tp")))
    s = jax.device_put(scale, NamedSharding(mesh, P()))
    return ids, s
