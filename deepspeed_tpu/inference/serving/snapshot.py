"""Crash-atomic serving snapshots — graceful-preemption state for the
continuous-batching engine, written through the SAME protocol as training
checkpoints (``runtime/fault/``): stage into ``<tag>.tmp/``, emit a
``MANIFEST.json`` with per-file sizes + checksums, fsync, atomic-rename,
atomic ``latest`` swap.  A kill at ANY instruction leaves either the
previous snapshot or the new one — never a half-written hybrid — and
``load_newest_snapshot`` walks back past corrupt/partial tags exactly
like checkpoint auto-resume does.

The payload is host bookkeeping only: per undrained request its prompt,
the tokens generated so far, the remaining budget/eos/deadline, plus the
scheduler's RNG lane state.  Device state (KV lanes, slot vectors) is
deliberately NOT saved — a resumed request re-prefills ``prompt +
generated`` through the ordinary admission path, whose greedy
continuation is bitwise-identical to the uninterrupted run (proven by
the kill-at-seam harness in ``tests/unit/test_serving_slo.py``)."""

import json
import os
import shutil

from deepspeed_tpu.runtime.fault.atomic import (atomic_publish_dir,
                                                atomic_write_text)
from deepspeed_tpu.runtime.fault.manifest import (build_manifest,
                                                  is_reserved_tag,
                                                  newest_valid_tag,
                                                  write_manifest)
from deepspeed_tpu.utils.logging import logger

SNAPSHOT_FILE = "serving_state.json"
SNAPSHOT_VERSION = 1


def save_snapshot(snapshot_dir, tag, state, checksum="sha256"):
    """Publish ``state`` (a JSON-serializable dict) crash-atomically as
    ``<snapshot_dir>/<tag>/`` and swap ``latest``.  Returns the tag."""
    tag = str(tag)
    if is_reserved_tag(tag):
        raise ValueError(f"snapshot tag {tag!r} collides with the staging "
                         "namespace (*.tmp / *.old.<pid>)")
    os.makedirs(snapshot_dir, exist_ok=True)
    staging = os.path.join(snapshot_dir, f"{tag}.tmp")
    if os.path.isdir(staging):           # a previous crash's orphan
        shutil.rmtree(staging)
    os.makedirs(staging)
    payload = dict(state)
    payload["version"] = SNAPSHOT_VERSION
    with open(os.path.join(staging, SNAPSHOT_FILE), "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
    write_manifest(staging, build_manifest(
        staging, tag, checksum=checksum,
        step_meta={"global_steps": int(state.get("seq", 0))}))
    atomic_publish_dir(staging, os.path.join(snapshot_dir, tag))
    atomic_write_text(os.path.join(snapshot_dir, "latest"), tag)
    logger.info(f"[serving] snapshot {tag}: "
                f"{len(state.get('requests', []))} undrained request(s)")
    return tag


def load_newest_snapshot(snapshot_dir):
    """``(tag, state)`` for the newest manifest-valid snapshot under
    ``snapshot_dir`` (walk-back past corrupt/partial tags), or
    ``(None, None)`` when there is nothing to resume."""
    if not snapshot_dir or not os.path.isdir(snapshot_dir):
        return None, None
    tag = newest_valid_tag(snapshot_dir, for_resume=True)
    if tag is None:
        return None, None
    path = os.path.join(snapshot_dir, tag, SNAPSHOT_FILE)
    try:
        with open(path) as f:
            state = json.load(f)
    except (OSError, ValueError) as e:
        # the manifest passed but the payload does not parse — treat it
        # like any other invalid tag and walk back past it
        logger.warning(f"[serving] snapshot {tag}: unreadable payload "
                       f"({e}) — walking back")
        older = newest_valid_tag(snapshot_dir, skip=(tag,), for_resume=True)
        if older is None:
            return None, None
        with open(os.path.join(snapshot_dir, older, SNAPSHOT_FILE)) as f:
            return older, json.load(f)
    if state.get("version") != SNAPSHOT_VERSION:
        logger.warning(f"[serving] snapshot {tag}: version "
                       f"{state.get('version')} != {SNAPSHOT_VERSION} — "
                       "ignoring")
        return None, None
    return tag, state


def read_snapshot_tag(snapshot_dir, tag):
    """Explicit-tag read (diagnostics / tests); manifest verification is
    the caller's concern — this only parses."""
    with open(os.path.join(snapshot_dir, str(tag), SNAPSHOT_FILE)) as f:
        return json.load(f)
