"""Continuous-batching serving engine — iteration-level scheduling over
``InferenceEngine`` (Orca, Yu et al. OSDI'22; slot/paged KV management in
the spirit of vLLM's PagedAttention, Kwon et al. SOSP'23 — here with the
TPU constraint that every program keeps FIXED shapes).

The scheduler loop per iteration (:meth:`ServingEngine.step`):

1. **Admission** — while a KV slot is free and the queue is non-empty,
   pop a request (``fcfs`` or ``shortest_first``) and stream its prompt
   through the engine's donated per-chunk prefill executable
   (a dedicated instance of the same chunk program the split-prefill
   ``generate()`` path replays) into a single-lane cache, spending at most
   ``prefill_token_budget`` prompt tokens per iteration so a long prompt
   cannot starve decoding.  A finished prefill dispatches ONE fused admit
   program (first-token sample + lane insert + in-program slot-state
   write).
2. **Decode** — ONE call of the single reusable decode-step program
   advances every live slot ``decode_block`` tokens (cache + slot state
   donated).  Rows that emit their ``eos`` (or exhaust ``max_new_tokens``)
   retire IN-PROGRAM; the host mirrors the retirement bookkeeping from the
   emitted tokens, frees their slots mid-flight, and hands the lanes to
   the admission queue — no request ever waits for a batch to finish.

**Latency-hiding (the tunneled-device lesson — each separate dispatch
costs ~0.1 s there):** the slot state lives ON DEVICE and every program
chains through it by data dependency, so the host never synchronizes
inside the dispatch path.  Token reads lag ONE event behind: the host
dispatches the next decode block first and only then materializes the
previous block's tokens, so the device (and the tunnel) stay busy while
the host does its scheduling bookkeeping.  The price is that a slot freed
in block N is re-admittable only from block N+2 — at most one block of
idle per retirement.

Because slot occupancy rides traced arguments, the whole server lifetime
compiles exactly ONE decode-step executable per (num_slots, cache_len,
block, sampling) configuration.  The serving programs compile once per
PROCESS and deliberately bypass the persistent cache layers — reloaded
serving executables corrupt the donated slot workspace (see the
``_persist_opt_out`` note in ``__init__``).

**Paged KV cache** (``serving.paged``, ``docs/serving.md`` "Paged KV
cache"): the per-slot monolithic lanes are replaced by one shared page
pool ``[L, num_pages, page_size, KVH*D]`` plus per-slot page tables the
host allocates and ships as TRACED arguments on every dispatch — HBM
cost becomes ``num_pages × page_size`` instead of ``num_slots ×
max_cache_len``, admission prefill writes straight into the slot's
pages (no staging lane, no admit-time insert), hash-matched prompt
prefixes map to the same refcounted physical pages (prefilled once,
copy-on-write at page granularity via recompute-on-divergence), and
pool pressure degrades into admission backpressure handled by the
bounded queue instead of an allocation cliff.  The int8 KV path
(``kv_cache_quant``) quantizes pool pages exactly like monolithic
lanes, roughly doubling page capacity.  Still exactly ONE decode
executable per server lifetime: page churn only changes table
CONTENTS, never a program shape.

**Speculative decoding** (``serving.speculative``, ``docs/serving.md``
"Speculative decoding"): a small DRAFT model proposes ``spec_k`` greedy
tokens per live slot from its own (always monolithic) KV workspace, and
the target model verifies the whole window in ONE batched forward —
accept mask, per-slot accepted length, eos/budget truncation and the
state update all computed IN-PROGRAM, the draft tokens flowing
propose → verify as a device array.  Up to ``spec_k + 1`` tokens commit
per target dispatch; every committed token is the target's own
``build_sample_fn`` output over exactly the committed history, so
greedy speculative serving is BITWISE-identical to the plain decode
path.  Fixed ``spec_k`` keeps the one-executable discipline: exactly
one draft-propose and one verify-and-commit executable per server
lifetime.  Admission streams each prompt chunk through BOTH models
(the draft lane rides the admit event one-behind like the target
lane); preemption snapshots committed tokens only, and restore
re-derives all draft state through the ordinary re-prefill path.

**Robustness / SLO layer** (``docs/serving.md`` "Robustness & SLOs"):
every request ends in a typed terminal status (``COMPLETED`` |
``SHED_DEADLINE`` | ``CANCELLED`` | ``ABORTED``); per-request wall-clock
deadlines shed queued work before it ever occupies a slot and retire
in-slot work at the next scheduling point; the queue is bounded
(``max_queue_depth`` + reject-or-block); a circuit breaker trips after N
consecutive failed dispatches and rejects-with-reason instead of
hammering a sick device; and graceful preemption (:meth:`preempt`)
drains in-flight slots under a budget then snapshots the remainder
through the crash-atomic checkpoint protocol, so a restarted server
(:meth:`restore`) resumes them with greedy outputs bitwise-identical to
an uninterrupted run.  All of it is host bookkeeping riding the existing
traced slot arguments — no new program shapes, the one-decode-executable
invariant holds through overload, drain and resume.

**Observability layer** (``docs/observability.md``): with
``serving.tracing`` on, every request carries a span tree (submit →
queue wait → admission prefill chunks → admit dispatch → decode /
spec-propose / spec-verify dispatches with tokens-committed counts →
terminal), recorded host-side at the existing scheduler seams,
exportable as Chrome trace-event JSON (:meth:`ServingEngine.dump_trace`,
Perfetto-loadable, one track per slot plus scheduler/queue/handler
tracks) and summarized as a queue/prefill/decode/host latency breakdown
on every :class:`~.slo.RequestResult`; TTFT, time-between-tokens,
queue-wait, per-program dispatch-duration and lock-wait histograms feed
``/metrics``.  With ``serving.flight_recorder`` on, a bounded
self-locked ring of recent structured events (dispatch begin/end,
scheduler decisions, breaker transitions, shed/cancel/abort reasons,
lock-wait samples, fault-injection hits) auto-dumps to JSON on
breaker-open, ``DrainTimeout``, ``ConcurrencyViolation`` and
scheduler-thread death, and on demand via ``GET /debug/flightrec``,
SIGUSR2 or :meth:`ServingEngine.dump_flightrec`.  Both are default-off
= seed behavior, host-side only (zero new jitted programs — the
zero-new-executables proof covers the tracing-on path), and the hot
path never contends a reader: the ring and the histograms carry their
own locks.
"""

import math
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.serving.concurrency import (
    InstrumentedRLock, checks_enabled, install_concurrency_checks)
from deepspeed_tpu.inference.serving.config import ServingConfig
from deepspeed_tpu.inference.serving.flightrec import FlightRecorder
from deepspeed_tpu.monitor.trace import ServingHistograms, SpanTracer
from deepspeed_tpu.inference.serving.paging import (PagePool,
                                                    PagedPoolWorkspace,
                                                    PrefixIndex,
                                                    compact_page_str,
                                                    pages_for)
from deepspeed_tpu.inference.serving.slo import (CircuitBreaker,
                                                 DrainTimeout, QueueFull,
                                                 RequestResult,
                                                 RequestStatus,
                                                 TERMINAL_STATUSES,
                                                 TokenStream)
from deepspeed_tpu.inference.serving.slots import (init_slot_state,
                                                   make_admit_fn,
                                                   make_decode_block_fn,
                                                   make_draft_admit_fn,
                                                   make_draft_chunk_fn,
                                                   make_draft_propose_fn,
                                                   make_paged_admit_fn,
                                                   make_paged_chunk_fn,
                                                   make_paged_decode_block_fn,
                                                   make_paged_spec_verify_fn,
                                                   make_spec_verify_fn)
from deepspeed_tpu.runtime.fault import inject
from deepspeed_tpu.utils.logging import log_dist, logger


@dataclass
class ServeRequest:
    """One queued/running generation request (host bookkeeping only).

    ``prefix`` holds tokens ALREADY generated in a previous server
    incarnation (graceful-preemption resume): admission prefills
    ``ids + prefix`` and the device decodes only the remaining budget —
    the greedy continuation is bitwise what the uninterrupted run would
    have produced.  ``deadline`` is an absolute ``time.monotonic()``
    instant (``None`` = no deadline).  ``priority`` is the admission
    lane (0 = most urgent; only meaningful with
    ``serving.priority_lanes > 1``); ``streamed`` counts the tokens
    already published to :meth:`ServingEngine.token_events`
    subscribers."""
    rid: int
    ids: np.ndarray                  # [P] int32 prompt
    max_new: int
    eos: int                         # -1 = never stop early
    submitted_it: int = 0
    tokens: list = field(default_factory=list)
    slot: Optional[int] = None
    finished_it: Optional[int] = None
    status: str = RequestStatus.QUEUED
    deadline: Optional[float] = None
    client_id: Any = None
    prefix: list = field(default_factory=list)
    submit_t: float = 0.0
    first_tok_t: Optional[float] = None
    priority: int = 0
    streamed: int = 0
    resumed: bool = False            # restored from a preempt snapshot
    # observability stamps (serving.tracing only; the tracer's clock, so
    # tests can inject a deterministic one) — the request's span-tree
    # boundaries: submit -> admission start -> admit dispatched ->
    # first token processed -> terminal; t_last_tok drives the
    # time-between-tokens histogram and is stamped ONCE per token at
    # the host-mirror drain (a TokenStream late-attach replay never
    # re-stamps it)
    t_trace: Optional[float] = None
    t_admit_start: Optional[float] = None
    t_prefill_done: Optional[float] = None
    t_first_tok: Optional[float] = None
    t_last_tok: Optional[float] = None

    @property
    def fill_ids(self):
        """What admission prefills: the prompt plus any resumed tokens."""
        if not self.prefix:
            return self.ids
        return np.concatenate(
            [self.ids, np.asarray(self.prefix, np.int32)])


class _PendingPrefill:
    """An admission in progress: the slot is reserved, the prompt streams
    chunk-by-chunk into the lane cache across scheduler iterations."""

    def __init__(self, req, slot, lane, ids_pad, n_chunks, fill_len):
        self.req, self.slot, self.lane = req, slot, lane
        self.ids_pad = ids_pad           # [1, n_chunks*C] int32
        self.n_chunks = n_chunks
        self.fill_len = fill_len         # real positions incl. resume prefix
        self.ci = 0                      # chunks completed
        self.sel = None                  # last-real-position logits [1,1,V]
        # paged admission: prefill starts at the shared-prefix boundary
        # (page-aligned); positions < start are served by shared pages
        self.start = 0
        self.fill_tokens = None          # full fill (prefix registration)
        # speculative serving: the DRAFT model's single-lane prefill
        # cache (the prompt's K/V must land in the draft cache too)
        self.draft_lane = None


class _LanePool:
    """Reusable single-lane prefill caches.  Several admissions can be in
    flight at once (the admit op that consumes a lane is processed one
    event behind), so this is a pool, not a single workspace slot — with
    the same donated-and-dead liveness check ``KVCacheWorkspace`` does."""

    def __init__(self, module):
        self._module = module
        self._lanes = []

    def take(self, cache_len, dtype):
        while self._lanes:
            lane = self._lanes.pop()
            if not any(getattr(l, "is_deleted", lambda: False)()
                       for l in jax.tree.leaves(lane)):
                return lane
        return self._module.init_cache(1, cache_len, dtype=dtype)

    def give_back(self, lane):
        if lane is not None:             # paged admissions have no lane
            self._lanes.append(lane)

    def release(self):
        self._lanes.clear()


class ServingEngine:
    """Slot-based continuous batching over an :class:`InferenceEngine`.

    ``submit()`` enqueues a request and returns its id; ``step()`` runs one
    scheduler iteration; ``drain()`` loops until everything submitted has
    finished and returns ``{rid: np.ndarray}`` where each output follows
    the ``generate()`` contract ``[prompt..., generated...]`` of length
    ``len(prompt) + max_new_tokens`` (eos-padded past early stops — under
    greedy decoding, bitwise what ``engine.generate()`` returns for the
    same request solo)."""

    # The concurrency contract (docs/tpu_lint.md "Concurrency
    # contracts"): every mutable piece of scheduler state is declared
    # lock-guarded in serving/concurrency.py GUARDED_FIELDS — tpu-lint's
    # TL008 checks each source access statically, and
    # DSTPU_CONCURRENCY_CHECKS=1 asserts the lock is held at runtime
    # (__init__ tail below).

    def __init__(self, engine, monitor=None, draft_module=None,
                 draft_params=None, **overrides):
        assert engine.params is not None, \
            "no parameters: set_params/init_params first"
        cfg = getattr(engine._config, "serving", None) or ServingConfig()
        if overrides:
            cfg = ServingConfig(**{**cfg.model_dump(), **overrides})
        self.engine = engine
        self.module = engine.module
        self.config = cfg
        self.monitor = monitor
        self.num_slots = int(cfg.num_slots)
        if self.num_slots < 1:
            raise ValueError(f"serving.num_slots={cfg.num_slots}: need >= 1")
        # lane length: multiple of 8 (the fused decode kernel's sublane
        # alignment — same rounding as required_cache_len)
        self.cache_len = -(-int(cfg.max_cache_len) // 8) * 8
        # admission chunk: align like the engine's prefill_chunk_size
        # (multiple of 8, floor 8, cap 512 — the chunk kernel's bounds)
        self.chunk = min(512, max(8, -(-int(cfg.prefill_chunk) // 8) * 8))
        max_seq = getattr(getattr(self.module, "config", None),
                          "max_seq_len", None)
        if max_seq is not None and self.cache_len > max_seq:
            logger.warning(
                f"serving.max_cache_len={self.cache_len} exceeds the "
                f"model's max_seq_len={max_seq} — positions past it will "
                f"fault on learned position embeddings")
        if cfg.admission not in ("fcfs", "shortest_first"):
            raise ValueError(f"serving.admission={cfg.admission!r}: "
                             f"one of 'fcfs', 'shortest_first'")
        self.block = max(1, int(cfg.decode_block))
        # ---- network front end: priority lanes + fairness ----
        self.priority_lanes = int(cfg.priority_lanes)
        if self.priority_lanes < 1:
            raise ValueError(f"serving.priority_lanes="
                             f"{cfg.priority_lanes}: need >= 1")
        if float(cfg.priority_aging_s) < 0:
            raise ValueError(f"serving.priority_aging_s="
                             f"{cfg.priority_aging_s}: need >= 0")
        if float(cfg.fairness_tokens_per_s) > 0:
            from deepspeed_tpu.inference.serving.frontend.fairness import \
                FairnessTracker
            self._fairness = FairnessTracker(
                float(cfg.fairness_tokens_per_s),
                float(cfg.fairness_window_s))   # guarded-by: _lock
        else:
            self._fairness = None               # guarded-by: _lock
        # ---- paged KV cache (docs/serving.md "Paged KV cache") ----
        self.paged = bool(cfg.paged)
        if self.paged:
            if not hasattr(type(self.module), "init_paged_cache"):
                raise ValueError(
                    f"serving.paged=True but "
                    f"{type(self.module).__name__} has no "
                    f"init_paged_cache — the paged pool needs model "
                    f"support (models/transformer.py)")
            # page size: multiple of 8 (sublane alignment), floor 8, and
            # the virtual lane rounds UP to a whole number of pages
            self.page = max(8, -(-int(cfg.page_size) // 8) * 8)
            # Pallas paged-attention kernels (default on); False = the
            # pre-kernel take_along_axis gather path, for A/B benching
            self.paged_kernel = bool(cfg.paged_kernel)
            self.cache_len = -(-self.cache_len // self.page) * self.page
            self.n_slot_pages = self.cache_len // self.page
            # pool size incl. the reserved trash page 0; auto = full
            # worst-case capacity (every slot at max_cache_len) — no HBM
            # savings but also no pool pressure
            self.num_pages = int(cfg.num_pages) \
                or self.num_slots * self.n_slot_pages + 1
            if self.num_pages < 2:
                raise ValueError(f"serving.num_pages={cfg.num_pages}: "
                                 f"need >= 2 (trash + 1 allocatable)")

        # ---- speculative decoding (docs/serving.md "Speculative
        # decoding"): draft model + the fixed verify window ----
        self.speculative = bool(cfg.speculative)
        self.spec_k = int(cfg.spec_k)
        if self.speculative:
            if cfg.do_sample:
                raise ValueError(
                    "serving.speculative=True requires greedy decoding "
                    "(do_sample=False): the verify-and-commit program's "
                    "bitwise contract is the target's greedy tokens — "
                    "lossless speculative SAMPLING is not implemented")
            if not 1 <= self.spec_k <= 64:
                raise ValueError(f"serving.spec_k={cfg.spec_k}: need "
                                 f"1 <= spec_k <= 64")
            draft_module, draft_params = self._resolve_draft(
                engine, cfg, draft_module, draft_params)
            self.draft_module = draft_module
            dvocab = getattr(getattr(draft_module, "config", None),
                             "vocab_size", None)
            tvocab = getattr(getattr(self.module, "config", None),
                             "vocab_size", None)
            if dvocab is not None and tvocab is not None \
                    and dvocab != tvocab:
                raise ValueError(
                    f"draft model vocab_size={dvocab} != target "
                    f"vocab_size={tvocab} — speculative verification "
                    f"compares token ids, the vocabularies must match")

        from deepspeed_tpu.inference.engine import (KVCacheWorkspace,
                                                    build_sample_fn)
        sample_fn = build_sample_fn(bool(cfg.do_sample),
                                    float(cfg.temperature),
                                    int(cfg.top_k), float(cfg.top_p))
        sampling_key = (bool(cfg.do_sample), float(cfg.temperature),
                        int(cfg.top_k), float(cfg.top_p))
        # which attention-kernel mode each program class dispatches
        # through (ops/transformer/registry.py — the same capability
        # probes the traced programs take, so bench records /
        # prefill_plan reasons attribute the path that actually ran)
        from deepspeed_tpu.ops.transformer.registry import (
            kernel_modes as _registry_modes)
        _pe = getattr(getattr(self.module, "config", None),
                      "position_embedding", None)
        self.kernel_modes = _registry_modes(
            paged=self.paged,
            disabled=self.paged and not getattr(self, "paged_kernel", True),
            has_bias=(_pe == "alibi"))
        self._decode_fn = self._propose_fn = self._verify_fn = None
        self._draft_chunk_fn = self._draft_admit_fn = None
        if self.paged:
            # paged programs: the pool + per-slot page tables replace the
            # monolithic slot lanes.  Page tables are traced arguments
            # (rebuilt host-side per dispatch), so page churn/sharing
            # never mints a new executable — still exactly ONE decode
            # signature per server lifetime.
            if self.speculative:
                self._verify_fn = make_paged_spec_verify_fn(
                    self.module, sample_fn, engine._deq, self.spec_k,
                    self.cache_len, paged_kernel=self.paged_kernel)
                engine._tags[id(self._verify_fn)] = (
                    "serving_spec_verify_paged", self.num_slots,
                    self.num_pages, self.page, self.spec_k, sampling_key,
                    self.paged_kernel)
            else:
                self._decode_fn = make_paged_decode_block_fn(
                    self.module, sample_fn, engine._deq, self.block,
                    self.cache_len, paged_kernel=self.paged_kernel)
                engine._tags[id(self._decode_fn)] = (
                    "serving_decode_paged", self.num_slots,
                    self.num_pages, self.page, self.block, sampling_key,
                    self.paged_kernel)
            self._admit_fn = make_paged_admit_fn(sample_fn)
            engine._tags[id(self._admit_fn)] = (
                "serving_admit_paged", self.num_slots, sampling_key)
        else:
            if self.speculative:
                self._verify_fn = make_spec_verify_fn(
                    self.module, sample_fn, engine._deq, self.spec_k,
                    self.cache_len)
                engine._tags[id(self._verify_fn)] = (
                    "serving_spec_verify", self.num_slots,
                    self.cache_len, self.spec_k, sampling_key)
            else:
                self._decode_fn = make_decode_block_fn(
                    self.module, sample_fn, engine._deq, self.block,
                    self.cache_len)
                # stable program tags → the engine's AOT path
                # persists/reloads these executables through the
                # compile_cache store
                engine._tags[id(self._decode_fn)] = (
                    "serving_decode", self.num_slots, self.cache_len,
                    self.block, sampling_key)
            self._admit_fn = make_admit_fn(sample_fn)
            engine._tags[id(self._admit_fn)] = (
                "serving_admit", self.num_slots, self.cache_len,
                sampling_key)
        if self.speculative:
            # the draft side: one propose program, one draft prefill
            # chunk, one draft lane insert — the draft KV cache is
            # ALWAYS monolithic lanes [L_d, num_slots, cache_len, ...]
            # (the draft model is small; paging its cache would buy
            # little and complicate the pool bookkeeping for nothing)
            self._draft_deq = engine._deq \
                if draft_module is self.module else None
            self._propose_fn = make_draft_propose_fn(
                draft_module, self._draft_deq, self.spec_k,
                self.cache_len)
            self._draft_chunk_fn = make_draft_chunk_fn(draft_module,
                                                       self._draft_deq)
            self._draft_admit_fn = make_draft_admit_fn()
            engine._tags[id(self._propose_fn)] = (
                "serving_spec_propose", self.num_slots, self.cache_len,
                self.spec_k)
            engine._tags[id(self._draft_chunk_fn)] = (
                "serving_spec_draft_prefill", self.chunk)
            engine._tags[id(self._draft_admit_fn)] = (
                "serving_spec_draft_admit", self.num_slots,
                self.cache_len)
        # The serving programs must NOT be reloaded from either
        # persistent cache layer (serialized-executable store OR the XLA
        # disk cache): they chain one donated slot workspace across three
        # different programs (chunk lane -> admit insert -> decode
        # blocks), and running ANY of them from a cross-process reloaded
        # artifact nondeterministically corrupts the slot cache — wrong
        # tokens, cross-lane mixing, one lane's KV clobbered the moment
        # another lane admits — or segfaults outright (reproduced and
        # bisected with the serving kill-harness driver: cache-less runs
        # are 100% stable, warm runs flake at ~25-50%; the train and
        # whole-batch generate paths show no such failures and keep both
        # layers).  The admission chunk program is a DEDICATED instance
        # (same body as the engine-shared ('chunkfill', C, 1) memo, via
        # _make_chunk_fn): the shared one may already sit in eng._aot as
        # a store-reloaded executable from warmup()/batch-1 split
        # prefill, and opting IT out would strip generate()'s batch-1
        # path of its caches.  Each server process compiles its three
        # serving programs once — the one-decode-executable-per-server-
        # lifetime invariant is untouched, and overload/drain/resume
        # cycles mint no further executables
        # (tests/unit/test_serving_slo.py).
        if self.paged:
            # paged prefill writes straight into the slot's pool pages
            # (no single-lane staging cache; the pool chains chunk ->
            # decode by donation)
            self._chunk_fn = make_paged_chunk_fn(
                self.module, engine._deq, paged_kernel=self.paged_kernel)
            engine._tags[id(self._chunk_fn)] = (
                "serving_prefill_paged", self.chunk, self.page,
                self.paged_kernel)
        else:
            self._chunk_fn = engine._make_chunk_fn()
            engine._tags[id(self._chunk_fn)] = ("serving_prefill",
                                                self.chunk)
        for fn in (self._decode_fn, self._admit_fn, self._chunk_fn,
                   self._verify_fn, self._propose_fn,
                   self._draft_chunk_fn, self._draft_admit_fn):
            if fn is not None:
                engine._persist_opt_out.add(id(fn))

        self._cache_ws = KVCacheWorkspace(self.module)
        self._lane_pool = _LanePool(self.module)
        if self.speculative:
            self._draft_params = draft_params
            self._draft_ws = KVCacheWorkspace(self.draft_module)
            self._draft_lanes = _LanePool(self.draft_module)  # guarded-by: _lock
            self._draft_cache = None                          # guarded-by: _lock
        if self.paged:
            self._pool_ws = PagedPoolWorkspace(self.module)
            self._pool = PagePool(self.num_pages)   # guarded-by: _lock
            self._prefix = PrefixIndex()            # guarded-by: _lock
            # host-owned page tables, shipped as a traced arg on every
            # dispatch: [num_slots, pages_per_slot]; 0 = the trash page
            self._page_table = np.zeros(
                (self.num_slots, self.n_slot_pages), np.int32)  # guarded-by: _lock
            self._slot_pages = {}        # slot -> [page ids]  # guarded-by: _lock
        self._cache = None               # guarded-by: _lock
        self._state = None               # device-resident state  # guarded-by: _lock
        # host mirror of slot occupancy, updated as events are PROCESSED
        # (it lags the device by the in-flight events — by design)
        self._mirror_active = np.zeros((self.num_slots,), bool)  # guarded-by: _lock
        self._slots = [None] * self.num_slots      # guarded-by: _lock
        self._free = deque(range(self.num_slots))  # guarded-by: _lock
        self._queue = deque()                      # guarded-by: _lock
        self._pending = None                       # guarded-by: _lock
        # dispatched-but-unprocessed device work, processed FIFO one
        # event behind the newest dispatch: ("decode", toks_dev) |
        # ("admit", req, slot, lane, first_dev)
        self._events = deque()                     # guarded-by: _lock
        self._rng = jax.random.key(int(cfg.seed))  # guarded-by: _lock
        self._next_rid = 0                         # guarded-by: _lock
        self._it = 0                               # guarded-by: _lock
        # ---- robustness / SLO state (docs/serving.md) ----
        if cfg.queue_policy not in ("reject", "block"):
            raise ValueError(f"serving.queue_policy={cfg.queue_policy!r}: "
                             f"one of 'reject', 'block'")
        self._requests = {}              # all known  # guarded-by: _lock
        self._results = {}               # terminal   # guarded-by: _lock
        self._pending_reports = {}       # -> step()  # guarded-by: _lock
        # ---- threading model (docs/serving.md "Network front end") ----
        # ONE lock guards every piece of mutable scheduler state (queue,
        # requests/results maps, slot mirror, stats, streams): submit()/
        # cancel()/status()/result()/token_events() are safe from any
        # thread.  step()/drain()/preempt() additionally enforce a
        # single SCHEDULER OWNER thread (_check_owner): the host mirror,
        # the in-flight event deque and the donated-buffer chain assume
        # exactly one driver, and a second thread racing the mirror
        # would corrupt slot bookkeeping even under the lock (the lag-
        # one protocol is stateful across calls).  _cond lets blocked
        # submit()s (queue_policy="block" from a non-owner thread) wait
        # for the owner's next step instead of stepping themselves.
        # the engine lock also meters wall time spent waiting on it per
        # thread class — Serving/lock_wait_s + /metrics (concurrency.py)
        self._lock = InstrumentedRLock()
        self._cond = threading.Condition(self._lock)
        self._owner_thread = None        # first step()  # guarded-by: _lock
        self._streams = {}               # rid->[stream]  # guarded-by: _lock
        # set by submit()/restore() so an idle scheduler-owner loop
        # (frontend/transport.py) can sleep instead of busy-polling
        self.wake = threading.Event()
        self._breaker = CircuitBreaker(cfg.breaker_threshold,
                                       cfg.breaker_cooldown_s)
        self._closed = False             # guarded-by: _lock
        self._close_report = []          # undrained rids  # guarded-by: _lock
        self._snap_seq = 0               # snapshot lineage  # guarded-by: _lock
        self._slot_last_dispatch = {}    # slot -> mono t  # guarded-by: _lock
        # observability (docs/serving.md): scheduler counters + the
        # slot-occupancy trace the correctness test asserts EOS-mid-flight
        # retirement against
        self.stats = {"iterations": 0, "decode_calls": 0,  # guarded-by: _lock
                      "decode_tokens": 0, "prefill_tokens": 0,
                      "completed": 0, "admitted": 0, "wall_secs": 0.0,
                      "sync_secs": 0.0, "shed": 0, "cancelled": 0,
                      "resumed": 0, "prefix_lookups": 0, "prefix_hits": 0,
                      "prefix_tokens_reused": 0, "page_evictions": 0,
                      "admission_stalls": 0, "fairness_rejected": 0,
                      "paged_attention_fallback": 0,
                      "stream_bridge_drops": 0,
                      "lock_wait_scheduler_s": 0.0,
                      "lock_wait_handler_s": 0.0}
        if self.speculative:
            # speculative-decoding observability (docs/serving.md
            # "Speculative decoding"): windows = (dispatch x live slot)
            # verify opportunities, each committing 1..spec_k+1 tokens;
            # accept_rate = accepted draft tokens / proposed draft
            # tokens; draft/verify secs are host dispatch wall time.
            # Every key is exported as a dstpu_serving_spec_* gauge by
            # /metrics (the stats sweep) and as Serving/spec_* monitor
            # events (_emit_metrics).
            self.stats.update({
                "spec_rounds": 0, "spec_windows": 0,
                "spec_committed_tokens": 0, "spec_accept_rate": 0.0,
                "spec_tokens_per_dispatch": 0.0,
                "spec_draft_secs": 0.0, "spec_verify_secs": 0.0,
                "spec_draft_fraction": 0.0})
        self.occupancy_trace = []        # (it, n_active)  # guarded-by: _lock
        # ---- observability layer (docs/observability.md): span tracer
        # + histograms + flight recorder.  All default-off = seed
        # behavior; all host-side (zero new jitted programs — the
        # zero-new-executables proof covers the tracing-on path too).
        self.tracing = bool(cfg.tracing)
        if self.tracing:
            self._tracer = SpanTracer(int(cfg.trace_max_spans))  # guarded-by: _lock
            # histograms carry their own per-bucket locks (the /metrics
            # scrape renders them WITHOUT the engine lock); the
            # InstrumentedRLock observer feeds per-acquire lock waits
            # straight into the lock-wait family
            self._hist = ServingHistograms()
            self._lock.on_wait = self._hist.lock_wait.observe
        else:
            self._tracer = None          # guarded-by: _lock
            self._hist = None
        self._inject_observer = None
        self._memwatch = None            # guarded-by: _lock
        if cfg.flight_recorder:
            # the ring is guarded by its OWN lock (flightrec.py): the
            # hot path appends without contending readers, and crash
            # paths (/debug/flightrec, SIGUSR2, ConcurrencyViolation)
            # read without the engine lock
            self._flightrec = FlightRecorder(
                int(cfg.flight_recorder_events),
                dump_dir=cfg.flight_recorder_dir or None)
            fr = self._flightrec
            self._inject_observer = inject.add_fire_observer(
                lambda point, action, hit: fr.record(
                    "fault_injection", point=point, action=action,
                    hit=hit))
        else:
            self._flightrec = None
        if cfg.memory_telemetry:
            # live HBM telemetry (docs/observability.md "Device memory
            # & roofline"): host-side sampler over the accelerator's
            # canonical memory reader, owner-reconciled against this
            # engine's known buffers; rides the flight recorder when
            # that is on.  Zero new executables — memory_stats() is a
            # PJRT host call
            from deepspeed_tpu.monitor.memwatch import DeviceMemorySampler
            self._memwatch = DeviceMemorySampler(
                interval_s=float(cfg.memory_sample_interval_s),
                owners_fn=self._device_memory_owners,
                flightrec=self._flightrec)
            self.stats.update({
                "hbm_bytes_in_use": 0, "hbm_peak_bytes": 0,
                "hbm_limit_bytes": 0, "hbm_owned_bytes": 0,
                "hbm_unattributed_bytes": 0, "memory_samples": 0})
        # classify lock waiters as scheduler vs handler; the ref is read
        # AFTER a successful acquire, i.e. lock-held (concurrency.py)
        self._lock._owner_ref = \
            lambda: object.__getattribute__(self, "_owner_thread")
        if checks_enabled():
            # DSTPU_CONCURRENCY_CHECKS=1: every guarded-field access now
            # asserts the lock is held — the runtime half of TL008, the
            # interleaving stress harness drives serving traffic with
            # this armed (tools/lint/interleave_check.py)
            install_concurrency_checks(self)

    @staticmethod
    def _resolve_draft(engine, cfg, draft_module, draft_params):
        """The draft model behind ``serving.speculative``: an explicitly
        passed ``(draft_module, draft_params)`` pair wins;
        ``spec_draft_model="self"`` drafts with the target model itself
        (accept rate 1.0 under greedy — the dispatch/batched-verify
        ceiling, at the cost of a second full-size KV cache and a
        doubled decode forward); an OPT preset name builds the
        architecture against the target's vocab and uses the given
        ``draft_params`` — or RANDOM weights with a loud warning
        (accept rate ~0; smoke/bench floor only).  Float draft params
        are cast to the engine's compute dtype like ``set_params``
        does."""
        if draft_module is None:
            name = (cfg.spec_draft_model or "").strip()
            if name == "self":
                if draft_params is not None:
                    raise ValueError(
                        "spec_draft_model='self' drafts with the TARGET "
                        "model's own weights, but draft_params was also "
                        "passed — silently ignoring them would run the "
                        "wrong draft; pass draft_module with those "
                        "params, or drop one of the two")
                return engine.module, engine._params
            if not name:
                raise ValueError(
                    "serving.speculative=True needs a draft model: pass "
                    "engine.serve(draft_module=..., draft_params=...) "
                    "or set serving.spec_draft_model ('self' = the "
                    "target drafts for itself; docs/serving.md "
                    "'Speculative decoding')")
            from deepspeed_tpu.models.opt import opt_model
            tcfg = getattr(engine.module, "config", None)
            draft_module = opt_model(
                name,
                vocab_size=getattr(tcfg, "vocab_size", 50272),
                max_seq_len=max(getattr(tcfg, "max_seq_len", 2048),
                                int(cfg.max_cache_len)),
                dtype=getattr(tcfg, "dtype", "bfloat16"))
            if draft_params is None:
                logger.warning(
                    f"serving.spec_draft_model={name!r} with no "
                    f"draft_params — RANDOM draft weights: the accept "
                    f"rate will be ~0 and speculation will SLOW decode; "
                    f"pass trained weights via "
                    f"engine.serve(draft_params=...)")
                draft_params = draft_module.init(
                    jax.random.key(0),
                    {"input_ids": jnp.zeros((1, 8), jnp.int32)})
        elif draft_params is None:
            raise ValueError("draft_module passed without draft_params")
        if draft_params is engine._params:
            return draft_module, draft_params
        # cast AND place replicated on the engine mesh (set_params'
        # discipline): unplaced draft params would compile the whole
        # draft program chain single-device, and its committed outputs
        # would then clash with the mesh-replicated slot state the
        # target programs produce
        from jax.sharding import NamedSharding, PartitionSpec
        cast = engine.compute_dtype
        put = jax.jit(
            lambda t: jax.tree.map(
                lambda p: p.astype(cast)
                if jnp.issubdtype(p.dtype, jnp.floating) else p, t),
            out_shardings=NamedSharding(engine.mesh, PartitionSpec()))
        return draft_module, put(draft_params)

    # ------------------------------------------------------------------ #
    # Observability: span tracing, flight recorder, histograms
    # (docs/observability.md) — host bookkeeping only, all default-off
    # ------------------------------------------------------------------ #
    @contextmanager
    def _observe_dispatch(self, program, **args):  # lock-held: _lock
        """Record one device dispatch at its scheduler seam: a span on
        the scheduler track + a dispatch-duration histogram sample
        (tracing) and a ``dispatch_begin``/``dispatch_end`` (or
        ``dispatch_error``) event pair (flight recorder).  The measured
        duration is the HOST dispatch call — the async-dispatch cost the
        latency-hiding protocol is built around — never a device sync.
        No-op passthrough when both are off."""
        tr, fr = self._tracer, self._flightrec
        if tr is None and fr is None:
            yield
            return
        t0 = tr.now() if tr is not None else time.monotonic()
        if fr is not None:
            fr.record("dispatch_begin", program=program, **args)
        try:
            yield
        except BaseException as e:
            if fr is not None:
                fr.record("dispatch_error", program=program,
                          error=f"{type(e).__name__}: {e}"[:200], **args)
            raise
        t1 = tr.now() if tr is not None else time.monotonic()
        if tr is not None:
            tr.add(program, "dispatch", t0, t1, track="scheduler", **args)
            self._hist.dispatch.observe(program, t1 - t0)
        if fr is not None:
            fr.record("dispatch_end", program=program,
                      dur_s=round(t1 - t0, 6), **args)

    def _trace_done(self, req, status):  # lock-held: _lock
        """Terminal-time tracing: compute the request's latency
        breakdown (the :class:`~.slo.RequestResult` fields — segments
        between the stamped span boundaries, the LAST reached phase
        absorbing the remainder, so the parts always sum to
        ``latency_s`` exactly) and emit its span tree onto its slot
        track (requests that never reached a slot land on the ``queue``
        track).  Returns ``{}`` with tracing off."""
        tr = self._tracer
        if tr is None or req.t_trace is None:
            return {}
        t_end = tr.now()
        t_sub = req.t_trace
        bd = {"latency_s": max(t_end - t_sub, 0.0)}
        prev = t_sub
        for name, nxt in (("queue_s", req.t_admit_start),
                          ("prefill_s", req.t_prefill_done),
                          ("host_s", req.t_first_tok),
                          ("decode_s", t_end)):
            if nxt is None:              # ended mid-phase: absorb rest
                bd[name] = max(t_end - prev, 0.0)
                break
            bd[name] = max(nxt - prev, 0.0)
            prev = nxt
        track = req.slot if req.slot is not None else "queue"
        cid = None if req.client_id is None else str(req.client_id)
        tr.add("request", "request", t_sub, t_end, track=track,
               rid=req.rid, client_id=cid, slot=req.slot,
               priority=req.priority, status=status,
               tokens=len(req.tokens))
        tr.add("queue", "phase", t_sub,
               t_end if req.t_admit_start is None else req.t_admit_start,
               track=track, rid=req.rid, phase="queue")
        if req.t_admit_start is not None:
            tr.add("prefill", "phase", req.t_admit_start,
                   t_end if req.t_prefill_done is None
                   else req.t_prefill_done,
                   track=track, rid=req.rid, phase="prefill")
        if req.t_first_tok is not None:
            tr.add("decode", "phase", req.t_first_tok, t_end,
                   track=track, rid=req.rid, phase="decode",
                   tokens=len(req.tokens))
        return bd

    def _flight_dump(self, reason):
        """Best-effort auto-dump: a failing dump must never mask the
        distress being recorded.  Returns the dump path or ``None``."""
        fr = self._flightrec
        if fr is None:
            return None
        try:
            path = fr.dump(reason)
            logger.warning(f"serving flight recorder dumped to {path} "
                           f"({reason})")
            return path
        except Exception as e:           # noqa: BLE001
            logger.warning(f"serving flight-recorder dump failed "
                           f"({reason}): {type(e).__name__}: {e}")
            return None

    def _detach_observability(self):  # lock-held: _lock
        """Engine retirement (close/preempt): unhook the process-global
        fault-injection observer and flush the monitor so short-lived
        serving processes never drop tail events."""
        if self._inject_observer is not None:
            inject.remove_fire_observer(self._inject_observer)
            self._inject_observer = None
        mon = self.monitor
        flush = getattr(mon, "flush", None)
        if callable(flush):
            try:
                flush()
            except Exception as e:       # noqa: BLE001
                logger.warning(f"serving monitor flush on retirement "
                               f"failed: {type(e).__name__}: {e}")

    def dump_trace(self, path):
        """Write the span ring as Chrome trace-event JSON to ``path``
        (Perfetto / ``chrome://tracing`` loadable: one track per slot
        plus scheduler/queue/handler tracks; ``docs/observability.md``).
        Raises with ``serving.tracing`` off.  Thread-safe — only the
        ring COPY happens under the engine lock; rendering and writing
        (tens of MB on a full ring) run outside it, so a live
        scheduler is never stalled for the serialization."""
        with self._lock:
            if self._tracer is None:
                raise RuntimeError(
                    "dump_trace(): serving.tracing is off — enable it "
                    "to record spans (docs/observability.md)")
            tracer = self._tracer
            snap = tracer.span_snapshot()    # (spans, added), lock-held
        return tracer.dump(path, spans=snap)

    def histograms(self):
        """The :class:`~deepspeed_tpu.monitor.trace.ServingHistograms`
        set (``None`` with ``serving.tracing`` off).  Internally locked
        — ``/metrics`` renders it without the engine lock."""
        return self._hist

    # ------------------------------------------------------------------ #
    # Device-memory telemetry (docs/observability.md "Device memory &
    # roofline") — host-side, serving.memory_telemetry, default off
    # ------------------------------------------------------------------ #
    def _device_memory_owners(self):  # lock-held: _lock
        """Bytes of every device buffer this engine can NAME — what the
        sampler reconciles against the accelerator-reported device
        total; the gap is the unattributed-bytes gauge.  Owner figures
        are ``nbytes`` sums (no device sync)."""
        from deepspeed_tpu.monitor.memwatch import tree_device_bytes
        owners = {"params": tree_device_bytes(self.engine._params)}
        key = "page_pool" if self.paged else "kv_slots"
        owners[key] = tree_device_bytes(self._cache)
        owners["slot_state"] = tree_device_bytes(self._state)
        lanes = tree_device_bytes(self._lane_pool._lanes)
        if self._pending is not None:
            lanes += tree_device_bytes(self._pending.lane)
        owners["prefill_lanes"] = lanes
        if self.speculative:
            owners["draft_kv"] = tree_device_bytes(self._draft_cache) \
                + tree_device_bytes(self._draft_lanes._lanes)
            if self._draft_params is not self.engine._params:
                owners["draft_params"] = \
                    tree_device_bytes(self._draft_params)
        return owners

    def _sample_memory(self):  # lock-held: _lock
        """The scheduler-seam sampling hook: interval-gated; folds the
        newest sample into ``stats`` (peak is monotone — the serving
        run's HBM watermark)."""
        if self._memwatch is None:
            return
        sample = self._memwatch.maybe_sample()
        if sample is not None:
            self._sample_memory_into_stats(sample)

    def memory_snapshot(self):
        """One locked on-demand device-memory sample (owner-reconciled)
        — ``None`` with ``serving.memory_telemetry`` off.  Thread-safe;
        ``/metrics`` renders the gauges from this."""
        with self._lock:
            if self._memwatch is None:
                return None
            sample = self._memwatch.sample()
            self._sample_memory_into_stats(sample)
            return sample

    def _sample_memory_into_stats(self, sample):  # lock-held: _lock
        st = self.stats
        st["hbm_bytes_in_use"] = sample["bytes_in_use"]
        st["hbm_peak_bytes"] = max(st["hbm_peak_bytes"],
                                   sample["peak_bytes_in_use"],
                                   sample["bytes_in_use"])
        st["hbm_limit_bytes"] = sample["bytes_limit"]
        st["hbm_owned_bytes"] = sample["owned_bytes"]
        st["hbm_unattributed_bytes"] = sample["unattributed_bytes"]
        st["memory_samples"] = self._memwatch.samples

    @property
    def flightrec_enabled(self):
        """Cheap enabled predicate — use this for gating, not
        :meth:`flightrec_snapshot` (which copies the whole ring)."""
        return self._flightrec is not None

    def flightrec_snapshot(self):
        """Point-in-time copy of the flight-recorder ring (``None``
        when ``serving.flight_recorder`` is off).  Never takes the
        engine lock."""
        fr = self._flightrec
        return None if fr is None else fr.snapshot()

    def dump_flightrec(self, reason="manual", path=None):
        """Dump the flight-recorder ring to a JSON file (default: under
        ``serving.flight_recorder_dir``); returns the path.  Raises
        with ``serving.flight_recorder`` off.  Never takes the engine
        lock — callable from signal handlers and crash paths."""
        fr = self._flightrec
        if fr is None:
            raise RuntimeError(
                "dump_flightrec(): serving.flight_recorder is off — "
                "enable it to record events (docs/observability.md)")
        return fr.dump(reason, path=path)

    # ------------------------------------------------------------------ #
    # Public API
    # ------------------------------------------------------------------ #
    def submit(self, input_ids, max_new_tokens=32, eos_token_id=-1,
               deadline_s=None, client_id=None, priority=0):
        """Enqueue one prompt; returns the request id.  The request must
        fit a slot lane: ``ceil(P/chunk)*chunk <= max_cache_len`` (chunked
        prefill writes the padded tail) and ``P + max_new_tokens <=
        max_cache_len``.

        ``deadline_s`` (seconds from now; ``None`` = the config's
        ``default_deadline_s``, ``0`` = already expired): past it the
        request is SHED from the queue before ever occupying a slot, or
        retired at the next scheduling point once in a slot — terminal
        status ``SHED_DEADLINE``.  ``client_id`` is an opaque correlation
        value round-tripped through results and preemption snapshots
        (snapshots store it as JSON: non-serializable values are
        stringified, tuples come back as lists); with fairness enabled
        (``serving.fairness_tokens_per_s > 0``) it is also the accounting
        key — an over-budget client's submit raises
        :class:`~.slo.QueueFull` (HTTP 429) until its window decays.
        ``priority`` is the admission lane, ``0 <= priority <
        serving.priority_lanes`` with 0 the most urgent; queued requests
        age one lane per ``serving.priority_aging_s`` seconds so low
        priority cannot starve.

        Thread-safe: any thread may submit (the engine lock serializes it
        against the scheduler owner's ``step()``).

        Raises :class:`~.slo.QueueFull` when the bounded queue is at
        ``max_queue_depth`` under the ``reject`` policy (``block`` runs
        scheduler iterations inline when called from the scheduler-owner
        thread, and waits for the owner to free a spot otherwise), and
        :class:`~.slo.CircuitOpen` while the dispatch breaker is open."""
        inject.fire("serving.pre_submit_lock")
        with self._lock:
            rid = self._submit_locked(input_ids, max_new_tokens,
                                      eos_token_id, deadline_s, client_id,
                                      priority)
        self.wake.set()                  # rouse an idle scheduler thread
        return rid

    def _submit_locked(self, input_ids, max_new_tokens, eos_token_id,  # lock-held: _lock
                       deadline_s, client_id, priority):
        if self._closed:
            raise RuntimeError(
                "submit() on a closed ServingEngine — close() retired it; "
                "create a fresh server with engine.serve()")
        ids = np.asarray(input_ids, np.int32).reshape(-1)
        P = int(ids.shape[0])
        max_new = int(max_new_tokens)
        if P < 1:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError(f"max_new_tokens={max_new}: need >= 1")
        priority = int(priority)
        if not 0 <= priority < self.priority_lanes:
            raise ValueError(
                f"priority={priority}: need 0 <= priority < "
                f"serving.priority_lanes={self.priority_lanes} "
                f"(0 = most urgent)")
        padded = -(-P // self.chunk) * self.chunk
        # speculative serving reserves spec_k-1 tail positions per lane:
        # a live lane's last verify window writes K/V for up to spec_k
        # draft tokens past its final committed position (overwritten or
        # never attended — but they must land INSIDE the lane)
        spec_tail = (self.spec_k - 1) if self.speculative else 0
        need = max(P + max_new + spec_tail, padded)
        if need > self.cache_len:
            raise ValueError(
                f"request needs {need} cache positions (prompt {P} + new "
                f"{max_new}"
                + (f" + speculative window reserve {spec_tail}"
                   if spec_tail else "")
                + f", chunk-padded {padded}) but slot lanes hold "
                f"{self.cache_len} — raise serving.max_cache_len or split "
                f"the request")
        if self.paged and pages_for(need, self.page) > self._pool.allocatable:
            # a request the POOL can never satisfy must not enter the
            # queue: with every other slot drained it would still stall
            # admission forever (the per-request check above only bounds
            # it against the virtual lane)
            raise ValueError(
                f"request needs {pages_for(need, self.page)} pages "
                f"({need} positions at page_size={self.page}) but the "
                f"pool holds {self._pool.allocatable} allocatable pages "
                f"(num_pages={self.num_pages} incl. trash) — raise "
                f"serving.num_pages or split the request")
        self._breaker.check_submit()         # reject-with-reason when open
        if self._fairness is not None and not self._fairness.allow(client_id):
            self.stats["fairness_rejected"] += 1
            if self._flightrec is not None:
                self._flightrec.record(
                    "fairness_reject",
                    client_id=None if client_id is None
                    else str(client_id))
            raise QueueFull(
                f"client {client_id!r} is over its fairness budget "
                f"({self._fairness.usage(client_id):.0f} window tokens "
                f">= {self._fairness.budget:.0f}) — retry after the "
                f"window decays (HTTP 429; docs/serving.md 'Network "
                f"front end')")
        self._apply_backpressure()
        if deadline_s is None and self.config.default_deadline_s > 0:
            deadline_s = self.config.default_deadline_s
        deadline = None if deadline_s is None \
            else time.monotonic() + float(deadline_s)
        req = ServeRequest(self._next_rid, ids, max_new, int(eos_token_id),
                           submitted_it=self._it, deadline=deadline,
                           client_id=client_id, submit_t=time.monotonic(),
                           priority=priority)
        self._next_rid += 1
        self._queue.append(req)
        self._requests[req.rid] = req
        if self._tracer is not None:
            # the span-tree root's start; submissions arrive on client
            # threads, so the instant marker lands on the handler track
            req.t_trace = self._tracer.now()
            self._tracer.add("submit", "request", req.t_trace,
                             track="handler", rid=req.rid,
                             priority=priority,
                             client_id=None if client_id is None
                             else str(client_id))
        if self._flightrec is not None:
            self._flightrec.record(
                "submit", rid=req.rid, prompt_len=P, max_new=max_new,
                priority=priority,
                client_id=None if client_id is None else str(client_id))
        return req.rid

    def _apply_backpressure(self):  # lock-held: _lock
        depth = int(self.config.max_queue_depth)
        if not depth or len(self._queue) < depth:
            return
        if self.config.queue_policy == "reject":
            raise QueueFull(
                f"serving queue at max_queue_depth={depth} "
                f"(policy=reject) — retry later or raise the bound")
        if self._owner_thread is not None \
                and self._owner_thread is not threading.current_thread():
            # block, from a NON-owner thread (an HTTP handler): wait for
            # the owner's step() to free a spot — stepping here would
            # race the host mirror.  _cond releases the engine lock while
            # waiting, so the owner keeps scheduling.
            while len(self._queue) >= depth:
                if self._closed:
                    raise RuntimeError(
                        "submit() on a closed ServingEngine — close() "
                        "retired it while this submit was blocked")
                if self._no_block_progress():
                    raise QueueFull(
                        f"serving queue at max_queue_depth={depth} and "
                        f"the blocked submit cannot make progress: "
                        f"{self._breaker.last_error or 'circuit open'}")
                self._cond.wait(timeout=0.05)
            return
        # block, from the owner (or a not-yet-owned engine): run the
        # scheduler inline until a spot frees.  Progress is guaranteed
        # while anything can retire or admit; an open breaker with an
        # idle scheduler cannot make progress — reject then.
        while len(self._queue) >= depth:
            if self._no_block_progress():
                raise QueueFull(
                    f"serving queue at max_queue_depth={depth} and the "
                    f"blocked submit cannot make progress: "
                    f"{self._breaker.last_error or 'circuit open'}")
            self.step()

    def _no_block_progress(self):  # lock-held: _lock
        return self._breaker.open and not self._breaker.allow_dispatch() \
            and not (self._events or self._mirror_active.any()
                     or self._pending is not None)

    def _known(self, rid, what):  # lock-held: _lock
        """The :class:`ServeRequest` for ``rid``, or a CLEAR ``KeyError``
        for ids this server never issued — a typo'd/stale rid must fail
        loudly, not look like a still-running request."""
        req = self._requests.get(rid)
        if req is None:
            raise KeyError(
                f"unknown request id {rid!r} — {what} on a request this "
                f"server never issued (submit() returned the valid ids)")
        return req

    def cancel(self, rid):
        """Client cancellation.  A queued request is retired immediately
        (never occupies a slot); an in-slot request is retired at this
        scheduling point — its slot returns to the free list and any
        tokens still in flight for it are discarded.  Terminal status
        ``CANCELLED``.  Returns ``False`` for already-terminal (or
        preempted) requests; raises ``KeyError`` for ids this server
        never issued.  Thread-safe."""
        inject.fire("serving.pre_cancel_lock")
        with self._lock:
            req = self._known(rid, "cancel()")
            if req.status in TERMINAL_STATUSES \
                    or req.status == RequestStatus.PREEMPTED:
                return False
            self.stats["cancelled"] += 1
            if req in self._queue:
                self._queue.remove(req)
                self._record_terminal(req, RequestStatus.CANCELLED,
                                      "cancelled while queued")
                self._cond.notify_all()      # a queue spot freed
                return True
            if self._pending is not None and self._pending.req is req:
                self._give_back_lanes(self._pending)
                self._free.append(int(self._pending.slot))
                self._release_slot_pages(self._pending.slot)
                self._pending = None
                self._record_terminal(req, RequestStatus.CANCELLED,
                                      "cancelled during admission prefill")
                return True
            self._record_terminal(req, RequestStatus.CANCELLED,
                                  f"cancelled in slot {req.slot}")
            self._retire_slot_host_side(req)
            return True

    def status(self, rid):
        """The request's :class:`~.slo.RequestStatus` string; ``KeyError``
        for ids this server never issued.  Thread-safe."""
        with self._lock:
            return self._known(rid, "status()").status

    def result(self, rid):
        """The terminal :class:`~.slo.RequestResult`, or ``None`` while
        the request is still queued/running; ``KeyError`` for ids this
        server never issued.  Thread-safe."""
        with self._lock:
            self._known(rid, "result()")
            return self._results.get(rid)

    def token_events(self, rid, on_event=None):
        """Subscribe to the request's per-token event stream — a
        :class:`~.slo.TokenStream` fed from the host-mirror drain point
        (one event behind the device, flushed as each ``decode_block``'s
        tokens are processed), so TTFT and time-between-tokens are
        observable per request without synchronizing the dispatch path.

        Subscribing replays everything already generated (and, for a
        terminal request, the typed ``end`` event), so the stream is
        lossless no matter when the consumer attaches; resumed requests
        replay their prior-incarnation tokens first.  ``on_event``
        bridges each push synchronously into another world (the HTTP
        transport passes ``loop.call_soon_threadsafe``); it must never
        block.  ``KeyError`` for ids this server never issued.
        Thread-safe."""
        inject.fire("serving.pre_subscribe_lock")
        with self._lock:
            req = self._known(rid, "token_events()")
            stream = TokenStream(rid, on_event=on_event,
                                 on_drop=self._count_stream_drop)
            for i, t in enumerate(req.tokens):
                stream.push({"event": "token", "rid": rid,
                             "index": i, "token": int(t)})
            if req.status in TERMINAL_STATUSES \
                    or req.status == RequestStatus.PREEMPTED:
                res = self._results.get(rid)
                stream.push({"event": "end", "rid": rid,
                             "status": req.status,
                             "detail": res.detail if res is not None
                             else ""})
            else:
                self._streams.setdefault(rid, []).append(stream)
            return stream

    def _count_stream_drop(self, rid, exc):  # lock-held: _lock
        """Dropped subscriber-bridge accounting — pushes only ever run
        under the engine lock, so the counter mutation inherits it (the
        ``TokenStream.push`` contract; slo.py logs the warning_once)."""
        self.stats["stream_bridge_drops"] += 1

    def _publish_progress(self, req):  # lock-held: _lock
        """Push the request's not-yet-streamed tokens to every subscriber
        (called under the lock at the host-mirror drain points — the
        per-token stream is exactly the retirement bookkeeping's view,
        one event behind the device)."""
        n = len(req.tokens)
        streams = self._streams.get(req.rid)
        if streams:
            for i in range(req.streamed, n):
                ev = {"event": "token", "rid": req.rid, "index": i,
                      "token": int(req.tokens[i])}
                for s in streams:
                    s.push(ev)
        req.streamed = n

    def _publish_end(self, req, status, detail=""):  # lock-held: _lock
        """The typed terminal event — exactly once, last; subscribers
        are dropped (late ``token_events()`` calls replay from the
        request record instead)."""
        self._publish_progress(req)
        streams = self._streams.pop(req.rid, None)
        if streams:
            ev = {"event": "end", "rid": req.rid, "status": status,
                  "detail": detail}
            for s in streams:
                s.push(ev)

    def _release_draft_workspaces(self):  # lock-held: _lock
        """Free every draft-side buffer (close/preempt teardown)."""
        if not self.speculative:
            return
        self._draft_cache = None
        self._draft_ws.release()
        self._draft_lanes.release()

    def _give_back_lanes(self, p):  # lock-held: _lock
        """Return a dropped admission's prefill lane(s) to their pools —
        the target lane and, under speculation, the draft lane."""
        self._lane_pool.give_back(p.lane)
        if self.speculative and p.draft_lane is not None:
            self._draft_lanes.give_back(p.draft_lane)
            p.draft_lane = None

    def _release_slot_pages(self, slot):  # lock-held: _lock
        """Paged mode: return a retired slot's pages to the pool (shared
        prefix pages just drop one reference) and point its table row at
        the trash page — the NEXT dispatch's table redirects the zombie
        lane's masked writes there, so a freed page can be reallocated
        immediately (any write the zombie already has in flight executes
        in device order BEFORE the new occupant's prefill and is either
        overwritten or masked — docs/serving.md "Paged KV cache")."""
        if not self.paged:
            return
        pages = self._slot_pages.pop(int(slot), None)
        if pages is not None:
            for pg in pages:
                self._pool.decref(pg)
        self._page_table[int(slot), :] = 0

    def _paging_reset(self):  # lock-held: _lock
        """Drop EVERY page mapping (pool bookkeeping, prefix index, all
        table rows) — the pool buffer died with a failed dispatch or was
        just (re)allocated, so no indexed content survives."""
        if not self.paged:
            return
        self._prefix.clear(self._pool)
        self._pool.reset()
        self._page_table[:] = 0
        self._slot_pages.clear()

    def _retire_slot_host_side(self, req):  # lock-held: _lock
        """Free a retired request's slot in the HOST MIRROR only — the
        device lane keeps masked-no-op decoding until the slot's next
        occupant's admit program overwrites its state wholesale (the same
        overwrite every admission performs), so retirement never needs a
        device round trip or a new program.  When the request's admit
        event is still in flight (mirror not yet active), the slot is
        freed by ``_process_admit`` when the event arrives."""
        s = req.slot
        if s is not None and self._mirror_active[s]:
            self._mirror_active[s] = False
            self._slots[s] = None
            self._free.append(int(s))
            self._release_slot_pages(s)

    def _record_terminal(self, req, status, detail):  # lock-held: _lock
        """Mark a non-COMPLETED terminal outcome and queue it for the
        next ``step()`` return (output ``None``)."""
        req.status = status
        req.finished_it = self._it
        ttft = (req.first_tok_t - req.submit_t) \
            if req.first_tok_t is not None else None
        self._results[req.rid] = RequestResult(
            rid=req.rid, status=status, output=None, detail=detail,
            client_id=req.client_id, submitted_it=req.submitted_it,
            finished_it=self._it, ttft_s=ttft,
            **self._trace_done(req, status))
        if self._flightrec is not None:
            self._flightrec.record("terminal", rid=req.rid,
                                   status=status, detail=detail[:200])
        self._pending_reports[req.rid] = None
        # result is recorded BEFORE the end event: a subscriber woken by
        # "end" can immediately read result(rid)
        self._publish_end(req, status, detail)

    def _shed_expired(self):  # lock-held: _lock
        """Deadline enforcement at the scheduling point: expired QUEUED
        requests are shed before admission (they never occupy a slot);
        expired pending-prefill / in-slot requests are retired host-side
        (see :meth:`_retire_slot_host_side`)."""
        now = time.monotonic()
        expired = [r for r in self._queue
                   if r.deadline is not None and now >= r.deadline]
        for req in expired:
            self._queue.remove(req)
            self.stats["shed"] += 1
            self._record_terminal(
                req, RequestStatus.SHED_DEADLINE,
                f"deadline expired {now - req.deadline:.3f}s ago while "
                f"queued (never occupied a slot)")
        p = self._pending
        if p is not None and p.req.deadline is not None \
                and now >= p.req.deadline:
            self._give_back_lanes(p)
            self._free.append(int(p.slot))
            self._release_slot_pages(p.slot)
            self._pending = None
            self.stats["shed"] += 1
            self._record_terminal(p.req, RequestStatus.SHED_DEADLINE,
                                  "deadline expired during admission "
                                  "prefill")
        for req in list(self._slots):
            if req is None or req.deadline is None or now < req.deadline \
                    or req.status in TERMINAL_STATUSES:
                continue
            self.stats["shed"] += 1
            self._record_terminal(req, RequestStatus.SHED_DEADLINE,
                                  f"deadline expired in slot {req.slot} "
                                  f"after {len(req.tokens)} token(s)")
            self._retire_slot_host_side(req)

    def _check_owner(self, what):
        """Bind the SCHEDULER OWNER to the first thread that drives the
        engine and refuse every other thread afterwards: the host mirror,
        the in-flight event deque and the donated-buffer chain are
        stateful ACROSS calls (the lag-one protocol), so two drivers
        corrupt slot bookkeeping even with every individual call locked.
        submit()/cancel()/status()/result()/token_events() stay callable
        from any thread — only the driving methods are owner-bound.

        A dedicated scheduler thread (frontend/transport.py) calls
        :meth:`bind_owner` BEFORE any request can arrive: without the
        eager claim, a blocked ``queue_policy="block"`` submit racing
        the owner's first ``step()`` could bind ITSELF as owner and
        wedge the real scheduler thread forever."""
        me = threading.current_thread()
        with self._lock:
            if self._owner_thread is None:
                self._owner_thread = me
                return
            if self._owner_thread is not me:
                raise RuntimeError(
                    f"{what} from thread {me.name!r} but this "
                    f"ServingEngine's scheduler owner is "
                    f"{self._owner_thread.name!r} — exactly one thread "
                    f"may drive step()/drain()/preempt() (the host "
                    f"mirror is stateful across calls); other threads "
                    f"use submit()/result()/cancel()/token_events() "
                    f"(docs/serving.md 'Network front end')")

    def bind_owner(self):
        """Eagerly claim the scheduler-owner role for the CURRENT thread
        (idempotent for the owner; raises for any other thread once
        bound).  A dedicated scheduler thread calls this before work can
        arrive, closing the race where a blocked ``block``-policy submit
        binds itself as owner ahead of the real driver's first
        ``step()``."""
        self._check_owner("bind_owner()")

    def release_owner(self):
        """Release the scheduler-owner binding — called by an EXITING
        owner thread (frontend/transport.py's scheduler loop on its way
        out) so a successor driver can claim the engine afterwards.
        Sequential handoff is safe: the mirror's cross-call state lives
        in the engine, the binding only exists to forbid CONCURRENT
        drivers.  No-op when unowned; raises from any non-owner thread
        (stealing the role while the owner lives is the bug the binding
        prevents)."""
        me = threading.current_thread()
        with self._lock:
            if self._owner_thread is None:
                return
            if self._owner_thread is not me:
                raise RuntimeError(
                    f"release_owner() from thread {me.name!r} but the "
                    f"scheduler owner is {self._owner_thread.name!r} — "
                    f"only the owner thread may release its binding")
            self._owner_thread = None

    def step(self):
        """One scheduler iteration: deadline shedding, admission prefill
        under the token budget, one decode-block dispatch, then process
        device results one event behind (latency-hiding).  Returns
        ``{rid: output}`` for every request that reached a terminal
        status this iteration — ``np.ndarray`` for ``COMPLETED``,
        ``None`` for shed/cancelled/aborted (typed detail via
        :meth:`result`).

        Owner-bound: the first thread to call a driving method
        (``step``/``drain``/``preempt``) becomes the scheduler owner and
        every other thread's call raises — see ``_check_owner``."""
        self._check_owner("step()")
        inject.fire("serving.pre_step_lock")
        with self._lock:
            return self._step_locked()

    def _step_locked(self):  # lock-held: _lock
        if self._closed:
            raise RuntimeError("step() on a closed ServingEngine")
        t0 = time.perf_counter()
        t0_tr = self._tracer.now() if self._tracer is not None else None
        inject.fire("serving.sigterm_at_iter")
        self._ensure_workspace()
        finished = {}
        self._shed_expired()
        if self._breaker.enabled:
            # breaker mode: dispatch failures are ABSORBED (the except
            # blocks below already restored the bookkeeping and recorded
            # ABORTED results) and counted; `threshold` consecutive ones
            # open the breaker — no dispatches until the cooldown's
            # half-open probe, and submit() rejects with the reason
            was_open = self._breaker.open
            dispatched = False
            try:
                if self._breaker.allow_dispatch():
                    self._admit()
                    dispatched = self._dispatch_decode()
            except Exception as e:
                self._breaker.record_failure(e)
                if self._flightrec is not None:
                    self._flightrec.record(
                        "breaker_failure",
                        consecutive=self._breaker.consecutive_failures,
                        threshold=self._breaker.threshold,
                        error=f"{type(e).__name__}: {e}"[:200])
                    if self._breaker.open and not was_open:
                        # the moment the server stops trusting its own
                        # device: capture what led here
                        self._flightrec.record(
                            "breaker_open", trips=self._breaker.trips,
                            last_error=self._breaker.last_error[:200])
                        self._flight_dump("breaker_open")
                logger.warning(
                    f"serving dispatch failure absorbed by the circuit "
                    f"breaker ({self._breaker.consecutive_failures}"
                    f"/{self._breaker.threshold} consecutive"
                    f"{'; OPEN' if self._breaker.open else ''}): "
                    f"{type(e).__name__}: {e}")
            if was_open and not self._breaker.open \
                    and self._flightrec is not None:
                self._flightrec.record("breaker_close",
                                       trips=self._breaker.trips)
        else:
            self._admit()
            dispatched = self._dispatch_decode()
        # lag-one processing: with fresh work in flight, leave the newest
        # event unread so the device/tunnel keeps running while the host
        # does bookkeeping; once nothing new was dispatched, flush fully
        self._process_events(finished, keep=1 if dispatched else 0)
        # lock-contention observability: cumulative wall time threads
        # spent WAITING on the engine lock, scheduler vs handlers
        # (InstrumentedRLock; exported via /metrics and Serving/ events)
        self.stats["lock_wait_scheduler_s"] = self._lock.wait_s["scheduler"]
        self.stats["lock_wait_handler_s"] = self._lock.wait_s["handler"]
        if self._flightrec is not None \
                and self.stats["iterations"] % 32 == 0:
            # periodic lock-wait sample: cheap cumulative snapshot so a
            # dump shows whether contention grew before the distress
            self._flightrec.record(
                "lock_wait",
                scheduler_s=round(self.stats["lock_wait_scheduler_s"], 6),
                handler_s=round(self.stats["lock_wait_handler_s"], 6))
        # interval-gated device-memory sample (serving.memory_telemetry;
        # a clock compare between samples)
        self._sample_memory()
        self._emit_metrics()
        self.stats["iterations"] += 1
        self.stats["wall_secs"] += time.perf_counter() - t0
        if self._tracer is not None:
            self._tracer.add("step", "scheduler", t0_tr,
                             self._tracer.now(), track="scheduler",
                             it=self._it)
        self._it += 1
        if self._pending_reports:
            finished.update(self._pending_reports)
            self._pending_reports.clear()
        # retirements/admissions may have freed queue spots: rouse
        # blocked non-owner submit()s (queue_policy="block")
        self._cond.notify_all()
        return finished

    def drain(self, timeout_s=None):
        """Run the scheduler until every submitted request has reached a
        terminal status; returns ``{rid: output}`` for everything that
        finished during the call (``None`` outputs for non-COMPLETED
        terminals).  ``timeout_s`` (default: the config's
        ``drain_timeout_s``; 0/None = no limit) bounds the wall clock —
        past it :class:`~.slo.DrainTimeout` is raised with per-slot
        diagnostics (slot id, request id, last dispatch age) instead of
        spinning forever on a wedged scheduler."""
        self._check_owner("drain()")
        if timeout_s is None:
            timeout = self.config.drain_timeout_s or None
        else:
            timeout = timeout_s or None      # explicit 0 = no limit
        t0 = time.monotonic()
        results = {}
        while self._work_outstanding():
            if timeout is not None and time.monotonic() - t0 > timeout:
                with self._lock:
                    diag = self._drain_diagnostics(timeout,
                                                   time.monotonic() - t0)
                if self._flightrec is not None:
                    # the dump's tail is the dispatch sequence that led
                    # into the wedge — what the diagnostics (a
                    # point-in-time view) cannot show
                    self._flightrec.record("drain_timeout",
                                           diag=diag[:400])
                    self._flight_dump("drain_timeout")
                raise DrainTimeout(diag)
            if self._breaker.open and not self._breaker.allow_dispatch() \
                    and not self._anything_in_flight():
                # open breaker, nothing in flight: don't busy-spin the
                # queue scan while waiting out the cooldown
                time.sleep(min(
                    0.01, self._breaker.seconds_until_half_open()))
            results.update(self.step())
        with self._lock:
            if self._pending_reports:
                results.update(self._pending_reports)
                self._pending_reports.clear()
        return results

    def _work_outstanding(self):
        """True while anything submitted has not reached a terminal
        status (queued, mid-prefill, in flight or mirror-active) — the
        locked point-in-time view ``drain()`` loops on (its old unlocked
        reads raced ``submit()``/``cancel()`` from other threads)."""
        with self._lock:
            return bool(self._queue or self._pending is not None
                        or self._events or self._mirror_active.any())

    def work_pending(self):
        """Public combined scheduler predicate: anything queued,
        mid-prefill, dispatched or mirror-live — ONE lock round-trip,
        for driving loops (``frontend/transport.py``, ``resilient.py``)
        that would otherwise take the lock three times per iteration
        through the individual monitoring properties.  Thread-safe."""
        return self._work_outstanding()

    def _anything_in_flight(self):
        """Locked: dispatched events unprocessed or mirror-live slots."""
        with self._lock:
            return bool(self._events or self._mirror_active.any())

    def _drain_diagnostics(self, timeout, elapsed):  # lock-held: _lock
        now = time.monotonic()
        lines = [f"drain() exceeded its {timeout:.1f}s wall-clock budget "
                 f"({elapsed:.1f}s elapsed) with work outstanding: "
                 f"queue={len(self._queue)}, "
                 f"in_flight_events={len(self._events)}"]
        for s, req in enumerate(self._slots):
            if req is None:
                continue
            last = self._slot_last_dispatch.get(s)
            age = f"{now - last:.1f}s ago" if last is not None else "never"
            lines.append(f"  slot {s}: request {req.rid} "
                         f"(status {req.status}, {len(req.tokens)} "
                         f"token(s), last dispatch {age})")
        if self._pending is not None:
            lines.append(f"  pending prefill: request "
                         f"{self._pending.req.rid} on slot "
                         f"{self._pending.slot} "
                         f"({self._pending.ci}/{self._pending.n_chunks} "
                         f"chunks)")
        if self._breaker.open:
            lines.append(f"  circuit breaker OPEN "
                         f"({self._breaker.consecutive_failures} "
                         f"consecutive failures; last: "
                         f"{self._breaker.last_error})")
        if self.paged:
            lines.append(f"  page pool: {self._pool.in_use}"
                         f"/{self._pool.allocatable} in use, "
                         f"{len(self._prefix)} prefix entries, "
                         f"{self.stats['admission_stalls']} admission "
                         f"stall(s)")
        return "\n".join(lines)

    def close(self):
        """Retire the server: abort everything undrained (queued,
        prefilling and in-slot requests all end ``ABORTED``), release the
        KV workspaces, and mark the engine closed — ``submit()``/
        ``step()`` afterwards raise.  Idempotent: every call returns the
        same sorted list of the request ids that were undrained at the
        first close."""
        with self._lock:
            return self._close_locked()

    def _close_locked(self):  # lock-held: _lock
        if self._closed:
            return list(self._close_report)
        finished = {}
        try:
            self._process_events(finished, keep=0)
        except Exception as e:               # dead buffers from a failure
            logger.warning(f"serving close(): discarding unreadable "
                           f"in-flight events ({type(e).__name__}: {e})")
        if finished:
            logger.warning(f"serving close(): {len(finished)} finished "
                           f"request(s) discarded unread")
        undrained = sorted(
            [r.rid for r in self._slots if r is not None]
            + ([self._pending.req.rid] if self._pending is not None else [])
            + [r.rid for r in self._queue])
        for req in list(self._queue):
            self._record_terminal(req, RequestStatus.ABORTED,
                                  "engine closed with the request still "
                                  "queued")
        self._queue.clear()
        self._abort_in_flight("close()")
        if self._cache is not None:
            if self.paged:
                self._pool_ws.give_back(self._cache)
            else:
                self._cache_ws.give_back(self._cache)
            self._cache = None
        self._state = None
        self._cache_ws.release()
        self._lane_pool.release()
        self._release_draft_workspaces()
        if self.paged:
            self._pool_ws.release()
        self._detach_observability()
        self._closed = True
        self._close_report = undrained
        # blocked submit()s must observe _closed and raise, idle
        # scheduler loops must notice the shutdown
        self._cond.notify_all()
        self.wake.set()
        if undrained:
            logger.warning(f"serving close(): {len(undrained)} undrained "
                           f"request(s) {undrained} aborted")
        return list(self._close_report)

    def _abort_in_flight(self, why):  # lock-held: _lock
        """Drop every request past admission (its KV rows live in buffers
        that are dead or about to be re-initialized) and restore the slot
        bookkeeping to all-free — queued requests survive and the next
        ``step()`` runs on a fresh workspace.  Without this, a failed
        decode dispatch would leak the occupied slots forever (drain()
        then spins: nothing free to admit, nothing active to decode) and
        stale events would replay against the fresh all-inactive state."""
        lost = []
        for req in self._slots:
            if req is None:
                continue
            lost.append(req.rid)
            if req.status not in TERMINAL_STATUSES:
                self._record_terminal(req, RequestStatus.ABORTED,
                                      f"in-flight request aborted: {why}")
        if self._pending is not None:
            req = self._pending.req
            lost.append(req.rid)
            if req.status not in TERMINAL_STATUSES:
                self._record_terminal(req, RequestStatus.ABORTED,
                                      f"admission aborted: {why}")
            self._give_back_lanes(self._pending)
            self._pending = None
        self._events.clear()
        self._slots = [None] * self.num_slots
        self._free = deque(range(self.num_slots))
        self._mirror_active[:] = False
        self._state = None
        if self.speculative:
            # the draft cache's contents mirror the aborted in-flight
            # requests (and may be donated-dead after a failed propose)
            # — drop it so the next step reallocates a fresh one
            self._draft_ws.give_back(self._draft_cache)
            self._draft_cache = None
        self._paging_reset()
        if lost:
            self.stats["aborted"] = self.stats.get("aborted", 0) + len(lost)
            if self._flightrec is not None:
                self._flightrec.record("abort_in_flight", why=why[:200],
                                       rids=lost)
            logger.warning(f"serving {why}: aborted {len(lost)} in-flight "
                           f"request(s) {lost} — queued requests survive")

    # Monitoring properties take the engine lock (re-entrant, so locked
    # callers like _emit_metrics/_metrics_body compose): an unlocked
    # read would race the scheduler mutating the same state — the
    # "/metrics iterating fairness state while the scheduler compacted
    # it" bug class TL008 exists to kill.
    @property
    def queue_depth(self):
        with self._lock:
            return len(self._queue) + (1 if self._pending is not None
                                       else 0)

    @property
    def active_slots(self):
        """Live slots as of the last PROCESSED event (the host mirror)."""
        with self._lock:
            return int(np.sum(self._mirror_active))

    @property
    def in_flight(self):
        """Dispatched device events not yet processed."""
        with self._lock:
            return len(self._events)

    @property
    def page_pool_utilization(self):
        """Allocated fraction of the page pool (0.0 when not paged)."""
        with self._lock:
            return self._pool.utilization() if self.paged else 0.0

    @property
    def prefix_hit_rate(self):
        """Fraction of prefix-cache lookups that matched >= 1 page."""
        with self._lock:
            n = self.stats["prefix_lookups"]
            return self.stats["prefix_hits"] / n if n else 0.0

    def health_snapshot(self):
        """One locked point-in-time view of the scheduler for health
        endpoints (``/healthz``): queue depth, mirror occupancy,
        in-flight events, breaker state, closed flag.  Thread-safe —
        the HTTP front end calls it through ``run_in_executor`` so the
        loop thread never blocks on the engine lock itself."""
        with self._lock:
            # the properties re-enter the already-held lock (re-entrant
            # acquires are excluded from the wait samples), so /healthz
            # and the property/metrics view share ONE implementation
            snap = {
                "closed": self._closed,
                "queue_depth": self.queue_depth,
                "active_slots": self.active_slots,
                "num_slots": self.num_slots,
                "in_flight_events": self.in_flight,
                "breaker": {
                    "open": self._breaker.open,
                    "consecutive_failures":
                        self._breaker.consecutive_failures,
                    "trips": self._breaker.trips,
                    "last_error": self._breaker.last_error,
                },
            }
            snap["slot_occupancy"] = snap["active_slots"] / self.num_slots
            if self.paged:
                snap["page_pool_utilization"] = self.page_pool_utilization
            return snap

    # ------------------------------------------------------------------ #
    # Warmup — compile (or reload) the expensive programs up front
    # ------------------------------------------------------------------ #
    def warmup(self, monitor=None):
        """AOT-compile the expensive serving programs (the decode block
        and the admission prefill chunk) against abstract arguments, once
        per process, up front — so the first requests do not pay the
        compile.  Returns ``{program: compile_seconds}`` (0.0 = this
        process already compiled it).  The serving programs deliberately
        bypass the persistent cache layers (see ``__init__``:
        cross-process reloaded serving executables corrupt the slot
        workspace), so a restarted server recompiles here rather than
        reloading.

        The fused admit program deliberately compiles on first use
        instead: it takes no ``params``, so an abstract-args compile would
        pin it to single-device input shardings while its runtime inputs
        (chunk-program outputs) carry the mesh's replicated sharding —
        first-use compilation sees the real shardings."""
        eng = self.engine
        N, S, C = self.num_slots, self.cache_len, self.chunk
        dtype = eng.compute_dtype
        if self.paged:
            cache = jax.eval_shape(
                lambda: self.module.init_paged_cache(
                    self.num_pages, self.page, dtype=dtype))
        else:
            cache = jax.eval_shape(
                lambda: self.module.init_cache(N, S, dtype=dtype))
            lane = jax.eval_shape(
                lambda: self.module.init_cache(1, S, dtype=dtype))
        state = {
            "token": jax.ShapeDtypeStruct((N,), jnp.int32),
            "pos": jax.ShapeDtypeStruct((N,), jnp.int32),
            "active": jax.ShapeDtypeStruct((N,), jnp.bool_),
            "remaining": jax.ShapeDtypeStruct((N,), jnp.int32),
            "eos": jax.ShapeDtypeStruct((N,), jnp.int32),
        }
        rng = jax.eval_shape(lambda: jax.random.key(0))
        report = {}

        def warm(fn, args, name):
            from deepspeed_tpu.runtime import compile_cache as cc
            sig = (id(fn),) + cc.abstract_signature(args)
            if sig in eng._aot:
                return {name: 0.0}
            compiled, dt, hit = eng._aot_compile(fn, args)
            if compiled is None:
                logger.warning(f"serving warmup: {name} failed to "
                               f"AOT-compile — it compiles on first use")
                return {}
            eng._aot[sig] = compiled
            return {name: 0.0 if hit else dt}

        if self.paged:
            row = jax.ShapeDtypeStruct((1, self.n_slot_pages), jnp.int32)
            tables = jax.ShapeDtypeStruct((N, self.n_slot_pages),
                                          jnp.int32)
            cargs = (eng._params, cache, row,
                     jax.ShapeDtypeStruct((1, C), jnp.int32),
                     jax.ShapeDtypeStruct((), jnp.int32),
                     jax.ShapeDtypeStruct((1,), jnp.int32))
            report.update(warm(self._chunk_fn, cargs,
                               f"serving_prefill_paged:c{C}p{self.page}"))
            if self.speculative:
                draft = jax.ShapeDtypeStruct((N, self.spec_k), jnp.int32)
                report.update(warm(
                    self._verify_fn,
                    (eng._params, cache, state, tables, draft, rng),
                    f"serving_spec_verify_paged:n{N}s{S}k{self.spec_k}"
                    f"p{self.page}"))
            else:
                report.update(warm(
                    self._decode_fn,
                    (eng._params, cache, state, tables, rng),
                    f"serving_decode_paged:n{N}s{S}b{self.block}"
                    f"p{self.page}"))
        else:
            cargs = (eng._params, lane,
                     jax.ShapeDtypeStruct((1, C), jnp.int32),
                     jax.ShapeDtypeStruct((), jnp.int32),
                     jax.ShapeDtypeStruct((1,), jnp.int32))
            report.update(warm(self._chunk_fn, cargs,
                               f"serving_prefill:c{C}"))
            if self.speculative:
                draft = jax.ShapeDtypeStruct((N, self.spec_k), jnp.int32)
                report.update(warm(
                    self._verify_fn,
                    (eng._params, cache, state, draft, rng),
                    f"serving_spec_verify:n{N}s{S}k{self.spec_k}"))
            else:
                report.update(warm(self._decode_fn,
                                   (eng._params, cache, state, rng),
                                   f"serving_decode:n{N}s{S}"
                                   f"b{self.block}"))
        if self.speculative:
            dcache = jax.eval_shape(
                lambda: self.draft_module.init_cache(N, S, dtype=dtype))
            dlane = jax.eval_shape(
                lambda: self.draft_module.init_cache(1, S, dtype=dtype))
            report.update(warm(
                self._propose_fn, (self._draft_params, dcache, state),
                f"serving_spec_propose:n{N}s{S}k{self.spec_k}"))
            report.update(warm(
                self._draft_chunk_fn,
                (self._draft_params, dlane,
                 jax.ShapeDtypeStruct((1, C), jnp.int32),
                 jax.ShapeDtypeStruct((), jnp.int32),
                 jax.ShapeDtypeStruct((1,), jnp.int32)),
                f"serving_spec_draft_prefill:c{C}"))
        for name, dt in report.items():
            log_dist(f"serving warmup[{name}]: "
                     + ("cached" if dt == 0.0 else f"{dt:.1f}s"), ranks=[0])
        mon = monitor or self.monitor
        if mon is not None and getattr(mon, "enabled", True):
            mon.write_events([(f"Compile/{name}_secs", dt, 0)
                              for name, dt in report.items()])
        return report

    # ------------------------------------------------------------------ #
    # Admission: queue -> prefill chunks -> fused admit dispatch
    # ------------------------------------------------------------------ #
    def _pop_request(self):  # lock-held: _lock
        if self.priority_lanes > 1:
            return self._pop_request_priority()
        if self.config.admission == "shortest_first":
            req = min(self._queue, key=lambda r: (len(r.ids), r.rid))
            self._queue.remove(req)
            return req
        return self._queue.popleft()

    def _pop_request_priority(self):  # lock-held: _lock
        """Priority lanes over the base admission order: pop the lowest
        EFFECTIVE lane, breaking ties with the configured policy (queue
        position for fcfs, prompt length for shortest_first).  Effective
        lane = ``priority - floor(waited / priority_aging_s)`` clamped at
        0, so a lane-``k`` request reaches lane 0 after at most
        ``k * priority_aging_s`` seconds queued — the aging bound that
        keeps sustained high-priority load from starving low priority
        (``priority_aging_s = 0`` disables aging: strict lanes)."""
        now = time.monotonic()
        aging = float(self.config.priority_aging_s)

        def lane(r):
            if aging <= 0:
                return r.priority
            return max(0, r.priority - int((now - r.submit_t) / aging))

        if self.config.admission == "shortest_first":
            req = min(self._queue,
                      key=lambda r: (lane(r), len(r.ids), r.rid))
        else:
            req = min(enumerate(self._queue),
                      key=lambda ir: (lane(ir[1]), ir[0]))[1]
        self._queue.remove(req)
        return req

    def _admit(self):  # lock-held: _lock
        limit = self.config.prefill_token_budget or math.inf
        spent = 0
        while spent < limit:
            if self._pending is None:
                if not self._queue or not self._free:
                    return
                req = self._pop_request()
                pend = self._start_prefill(req)
                if pend is None:
                    # paged pool pressure: not enough free pages even
                    # after evicting unreferenced prefix pages — the
                    # request waits at the queue head until retirements
                    # free pages (backpressure, never a partial grab)
                    self._queue.appendleft(req)
                    self.stats["admission_stalls"] += 1
                    if self._flightrec is not None:
                        self._flightrec.record(
                            "admission_stall", rid=req.rid,
                            pool_in_use=self._pool.in_use
                            if self.paged else None)
                    return
                if self._tracer is not None and req.t_trace is not None:
                    # queue phase ends here: admission decided, the slot
                    # is reserved and prefill chunks start streaming
                    req.t_admit_start = self._tracer.now()
                    self._hist.queue_wait.observe(
                        req.t_admit_start - req.t_trace)
                if self._flightrec is not None:
                    self._flightrec.record(
                        "admit_start", rid=req.rid, slot=req.slot,
                        fill_len=pend.fill_len, chunks=pend.n_chunks)
                if self._fairness is not None and not req.resumed:
                    # charge admitted prefill work once, when admission
                    # actually starts (a paged stall above retries the
                    # same request without double-charging).  Resumed
                    # requests charge NOTHING here: their prompt and
                    # generated-so-far tokens were billed in the prior
                    # incarnation and ride the snapshot balance — the
                    # re-prefill is the server's preemption cost, not
                    # the client's
                    self._fairness.charge(req.client_id,
                                          len(req.fill_ids))
                self._pending = pend
            done = self._run_prefill_chunk(self._pending)
            spent += self.chunk
            if done:
                pend, self._pending = self._pending, None
                self._dispatch_admit(pend)

    def _start_prefill(self, req):  # lock-held: _lock
        fill = req.fill_ids              # prompt + any resumed tokens
        P = len(fill)
        if self.paged:
            return self._start_prefill_paged(req, fill, P)
        slot = self._free.popleft()
        req.slot = slot
        req.status = RequestStatus.PREFILLING
        n = -(-P // self.chunk)
        ids_pad = np.zeros((1, n * self.chunk), np.int32)
        ids_pad[0, :P] = fill
        lane = self._lane_pool.take(self.cache_len,
                                    self.engine.compute_dtype)
        pend = _PendingPrefill(req, slot, lane, ids_pad, n, P)
        if self.speculative:
            pend.draft_lane = self._draft_lanes.take(
                self.cache_len, self.engine.compute_dtype)
        return pend

    def _start_prefill_paged(self, req, fill, P):  # lock-held: _lock
        """Paged admission: map the longest indexed prefix (full pages,
        refcounted — prefilled ONCE per unique prefix), allocate private
        pages for the rest of the virtual lane, and prefill only from
        the shared boundary on.  Returns ``None`` (nothing popped,
        nothing allocated) when the pool cannot back the request yet."""
        dev_new = req.max_new - len(req.prefix)
        matched = []
        if self.config.prefix_cache and not self.speculative:
            # cap the match so the block holding the LAST prompt position
            # is always recomputed: admission samples the first token
            # from that position's logits, so at least one chunk must run
            # (speculative serving skips prefix sharing: the DRAFT cache
            # has no page pool, so its prefill must run from position 0
            # anyway — a shared target prefix would leave the draft side
            # unfilled; docs/serving.md "Speculative decoding")
            matched = self._prefix.lookup(fill, self.page, self._pool,
                                          (P - 1) // self.page)
        m = len(matched)
        # the prefill start must be CHUNK-aligned, not just page-aligned:
        # chunk ci writes the full padded span [s0+ci*C, s0+(ci+1)*C),
        # and only a chunk-aligned s0 keeps the padded end at
        # ceil(P/C)*C — the bound submit() already checked against the
        # lane.  A page-aligned-only start can pad PAST the table row
        # (page 16, chunk 64, P=120, m=7: 112+64=176 > 8-page lane)
        g = self.chunk // math.gcd(self.page, self.chunk)
        if m % g:
            for pg in matched[(m // g) * g:]:
                self._pool.decref(pg)
            matched = matched[:(m // g) * g]
            m = len(matched)
        s0 = m * self.page               # prefill start
        n_chunks = -(-(P - s0) // self.chunk)
        # the slot's virtual extent: decode writes through P+dev_new-1,
        # the padded last chunk writes through s0+n_chunks*C-1
        virt = max(P + dev_new, s0 + n_chunks * self.chunk)
        need_private = pages_for(virt, self.page) - m
        got = self._pool.alloc(need_private)
        if got is None and self.config.prefix_cache:
            freed = self._prefix.evict(
                self._pool, need_private - self._pool.free_count)
            self.stats["page_evictions"] += freed
            got = self._pool.alloc(need_private)
        if got is None:
            for pg in matched:
                self._pool.decref(pg)
            return None
        if self.config.prefix_cache and not self.speculative:
            # stats count ADMISSIONS, not stalled retries of the same
            # request (a 50-step stall must not record 50 lookups/hits)
            self.stats["prefix_lookups"] += 1
            if matched:
                self.stats["prefix_hits"] += 1
                self.stats["prefix_tokens_reused"] += m * self.page
        slot = self._free.popleft()
        req.slot = slot
        req.status = RequestStatus.PREFILLING
        row = matched + got
        self._slot_pages[slot] = row
        self._page_table[slot, :] = 0
        self._page_table[slot, :len(row)] = row
        ids_pad = np.zeros((1, n_chunks * self.chunk), np.int32)
        ids_pad[0, :P - s0] = fill[s0:]
        pend = _PendingPrefill(req, slot, None, ids_pad, n_chunks, P)
        pend.start = s0
        pend.fill_tokens = fill
        if self.speculative:
            # s0 == 0 under speculation (prefix sharing disabled), so
            # the draft lane prefills the same chunk spans as the pool
            pend.draft_lane = self._draft_lanes.take(
                self.cache_len, self.engine.compute_dtype)
        return pend

    def _run_prefill_chunk(self, p):  # lock-held: _lock
        C = self.chunk
        P = p.fill_len
        # chunk ci covers absolute positions [start + ci*C, start +
        # (ci+1)*C); start > 0 only for paged shared-prefix admissions
        local = int(min(max(P - 1 - p.start - p.ci * C, 0), C - 1))
        try:
            with self._observe_dispatch("prefill_chunk", rid=p.req.rid,
                                        slot=p.slot, chunk=p.ci,
                                        phase="prefill"):
                if self.paged:
                    # the chunk writes straight into the slot's pool
                    # pages — the POOL is the donated buffer, chained
                    # with decode
                    row = jnp.asarray(
                        self._page_table[p.slot:p.slot + 1])
                    logits, self._cache = self.engine._run_guarded(
                        self._chunk_fn,
                        (self.engine._params, self._cache, row,
                         jnp.asarray(
                             p.ids_pad[:, p.ci * C:(p.ci + 1) * C]),
                         jnp.asarray(p.start + p.ci * C, jnp.int32),
                         jnp.asarray([local], jnp.int32)))
                else:
                    logits, p.lane = self.engine._run_guarded(
                        self._chunk_fn,
                        (self.engine._params, p.lane,
                         jnp.asarray(
                             p.ids_pad[:, p.ci * C:(p.ci + 1) * C]),
                         jnp.asarray(p.ci * C, jnp.int32),
                         jnp.asarray([local], jnp.int32)))
        except BaseException as e:
            if self.paged:
                # the donated POOL may be dead — this is a decode-grade
                # failure: every in-flight request's KV lived in it
                self._pool_ws.give_back(self._cache)
                self._cache = None
                if p.req.status not in TERMINAL_STATUSES:
                    self._record_terminal(
                        p.req, RequestStatus.ABORTED,
                        f"admission prefill dispatch failed: "
                        f"{type(e).__name__}: {e}")
                self._abort_in_flight(
                    f"paged prefill dispatch failed "
                    f"(request {p.req.rid} lost)")
                raise
            # the donated lane may be dead — drop only THIS admission
            # (the decode workspace is untouched by a prefill failure)
            self._give_back_lanes(p)
            self._free.append(int(p.slot))
            self._pending = None
            if p.req.status not in TERMINAL_STATUSES:
                self._record_terminal(
                    p.req, RequestStatus.ABORTED,
                    f"admission prefill dispatch failed: "
                    f"{type(e).__name__}: {e}")
                self.stats["aborted"] = self.stats.get("aborted", 0) + 1
            logger.warning(f"serving prefill failed — request "
                           f"{p.req.rid} dropped")
            raise
        if self.speculative:
            # mirror the chunk into the DRAFT lane: speculation proposes
            # from the draft model's own cache, so it needs the prompt's
            # K/V too (same spans — prefix sharing is disabled under
            # speculation, p.start is always 0)
            t0s = time.perf_counter()
            try:
                with self._observe_dispatch("draft_prefill_chunk",
                                            rid=p.req.rid, slot=p.slot,
                                            chunk=p.ci, phase="prefill"):
                    _, p.draft_lane = self.engine._run_guarded(
                        self._draft_chunk_fn,
                        (self._draft_params, p.draft_lane,
                         jnp.asarray(
                             p.ids_pad[:, p.ci * C:(p.ci + 1) * C]),
                         jnp.asarray(p.start + p.ci * C, jnp.int32),
                         jnp.asarray([local], jnp.int32)))
            except BaseException as e:
                # the donated draft lane may be dead — drop only THIS
                # admission.  The target side's partial writes are freed
                # with the slot (monolithic lane back to the pool, paged
                # pages decref'd) and overwritten by the next occupant
                # before any of its queries attend them.
                self._give_back_lanes(p)
                self._free.append(int(p.slot))
                self._release_slot_pages(p.slot)
                self._pending = None
                if p.req.status not in TERMINAL_STATUSES:
                    self._record_terminal(
                        p.req, RequestStatus.ABORTED,
                        f"draft prefill dispatch failed: "
                        f"{type(e).__name__}: {e}")
                    self.stats["aborted"] = \
                        self.stats.get("aborted", 0) + 1
                logger.warning(f"serving draft prefill failed — request "
                               f"{p.req.rid} dropped")
                raise
            self.stats["spec_draft_secs"] += time.perf_counter() - t0s
        self._breaker.record_success()
        if (P - 1 - p.start) // C == p.ci:
            # this chunk held the prompt's last real position — its
            # selected logits seed the first sampled token (device-side;
            # never synchronized here)
            p.sel = logits
        p.ci += 1
        self.stats["prefill_tokens"] += C
        return p.ci >= p.n_chunks

    def _dispatch_admit(self, p):  # lock-held: _lock
        """Prefill complete: ONE fused dispatch samples the first token,
        inserts the lane and writes the slot state in-program.  The first
        token is read lazily when the event is processed.  A resumed
        request (non-empty ``prefix``) admits with the REMAINING token
        budget — its prefix already counts against ``max_new``."""
        req = p.req
        dev_new = req.max_new - len(req.prefix)
        self._rng, sub = jax.random.split(self._rng)
        try:
            inject.fire("serving.pre_admit")
            with self._observe_dispatch("admit", rid=req.rid,
                                        slot=int(p.slot),
                                        phase="admit"):
                if self.paged:
                    # the prompt's K/V already sits in the slot's pages
                    # — paged admission is just the first-token sample +
                    # the in-program slot-state write (state donated)
                    self._state, first = self.engine._run_guarded(
                        self._admit_fn,
                        (self._state, p.sel, sub,
                         jnp.asarray(p.slot, jnp.int32),
                         jnp.asarray(p.fill_len, jnp.int32),
                         jnp.asarray(dev_new, jnp.int32),
                         jnp.asarray(req.eos, jnp.int32)))
                else:
                    self._cache, self._state, first = \
                        self.engine._run_guarded(
                            self._admit_fn,
                            (self._cache, self._state, p.lane, p.sel, sub,
                             jnp.asarray(p.slot, jnp.int32),
                             jnp.asarray(p.fill_len, jnp.int32),
                             jnp.asarray(dev_new, jnp.int32),
                             jnp.asarray(req.eos, jnp.int32)))
        except BaseException as e:
            # cache/state were donated — same recovery as a decode
            # failure (this admission's request is lost with them).
            # Paged: only the STATE died (the pool is not an admit
            # argument); _abort_in_flight still resets all paging
            # bookkeeping, so stale KV is never attended.
            if not self.paged:
                self._cache_ws.give_back(self._cache)
                self._cache = None
            self._give_back_lanes(p)
            if req.status not in TERMINAL_STATUSES:
                self._record_terminal(req, RequestStatus.ABORTED,
                                      f"admit dispatch failed: "
                                      f"{type(e).__name__}: {e}")
            self._abort_in_flight(f"admit dispatch failed "
                                  f"(request {req.rid} lost)")
            raise
        self._breaker.record_success()
        if self.paged and self.config.prefix_cache \
                and not self.speculative and p.fill_tokens is not None:
            # index this request's full-prompt pages as sharable —
            # their prefill writes are complete (dispatched before this
            # admit) and nothing ever writes them again (the slot's own
            # writes land at positions >= fill_len)
            self._prefix.register(p.fill_tokens, self.page,
                                  self._slot_pages[p.slot], self._pool,
                                  p.fill_len // self.page)
        if self.speculative:
            # insert the prefilled draft lane into the draft cache (the
            # draft-side twin of the target admit's lane insert)
            t0s = time.perf_counter()
            try:
                with self._observe_dispatch("draft_admit", rid=req.rid,
                                            slot=int(p.slot),
                                            phase="admit"):
                    self._draft_cache = self.engine._run_guarded(
                        self._draft_admit_fn,
                        (self._draft_cache, p.draft_lane,
                         jnp.asarray(p.slot, jnp.int32)))
            except BaseException as e:
                # the donated draft cache may be dead — decode-grade
                # failure: every live slot's draft K/V lived in it
                self._give_back_lanes(p)
                if req.status not in TERMINAL_STATUSES:
                    self._record_terminal(
                        req, RequestStatus.ABORTED,
                        f"draft admit dispatch failed: "
                        f"{type(e).__name__}: {e}")
                self._abort_in_flight(f"draft admit dispatch failed "
                                      f"(request {req.rid} lost)")
                raise
            self.stats["spec_draft_secs"] += time.perf_counter() - t0s
        self._slot_last_dispatch[int(p.slot)] = time.monotonic()
        req.status = RequestStatus.RUNNING
        self._slots[p.slot] = req
        self._events.append(("admit", req, p.slot, p.lane, first,
                             p.draft_lane))
        self.stats["admitted"] += 1
        if self._tracer is not None and req.t_admit_start is not None:
            # prefill phase ends: the fused admit is dispatched; what
            # follows until the first token is PROCESSED is the lag-one
            # host window the breakdown books as host_s
            req.t_prefill_done = self._tracer.now()

    # ------------------------------------------------------------------ #
    # Decode: one block of the single reusable decode-step program
    # ------------------------------------------------------------------ #
    def _dispatch_decode(self):  # lock-held: _lock
        # dispatch when anything can be live on device: a slot active as
        # of the mirror, or an unprocessed admit that (probably) went live
        if not (self._mirror_active.any()
                or any(e[0] == "admit" for e in self._events)):
            return False
        self._rng, sub = jax.random.split(self._rng)
        try:
            inject.fire("serving.pre_decode_dispatch")
            if self.speculative:
                ev = self._dispatch_spec(sub)
            else:
                with self._observe_dispatch(
                        "decode", phase="decode",
                        live_slots=int(self._mirror_active.sum())):
                    if self.paged:
                        toks, self._cache, self._state = \
                            self.engine._run_guarded(
                                self._decode_fn,
                                (self.engine._params, self._cache,
                                 self._state,
                                 jnp.asarray(self._page_table), sub))
                    else:
                        toks, self._cache, self._state = \
                            self.engine._run_guarded(
                                self._decode_fn,
                                (self.engine._params, self._cache,
                                 self._state, sub))
                ev = ("decode", toks)
        except BaseException:
            # the donated cache/state may be dead — drop them so the next
            # step's workspace take() reallocates, and abort everything
            # past admission (its KV rows died with the buffers; stale
            # events/slot bookkeeping must not survive into the fresh
            # state).  Queued requests are untouched.
            if self.paged:
                self._pool_ws.give_back(self._cache)
            else:
                self._cache_ws.give_back(self._cache)
            self._cache = None
            self._abort_in_flight("decode dispatch failed")
            raise
        self._breaker.record_success()
        now = time.monotonic()
        for s, r in enumerate(self._slots):
            if r is not None:
                self._slot_last_dispatch[s] = now
        self._events.append(ev)
        self.stats["decode_calls"] += 1
        if self.paged and self.kernel_modes["decode"] == "reference_fallback":
            # this decode dispatch took the take_along_axis gather path
            # (serving.paged_kernel=False, or no Pallas / alibi) — the
            # BENCH_r04 bs128 cliff, surfaced instead of silent
            self.stats["paged_attention_fallback"] += 1
        return True

    def _dispatch_spec(self, sub):  # lock-held: _lock
        """One speculative round, two device-chained dispatches and zero
        host syncs: the draft proposes ``spec_k`` greedy tokens per slot
        from its OWN cache (draft cache donated through), then the
        target verifies the whole window in ONE batched forward and
        commits the accepted prefix in-program (cache + slot state
        donated).  The draft tokens never touch the host — they flow
        propose → verify as a device array.  A failure in either
        dispatch is handled by the caller's decode-failure recovery
        (``_abort_in_flight`` drops the draft cache too)."""
        live = int(self._mirror_active.sum())
        t0 = time.perf_counter()
        with self._observe_dispatch("spec_propose", phase="decode",
                                    live_slots=live):
            draft, self._draft_cache = self.engine._run_guarded(
                self._propose_fn,
                (self._draft_params, self._draft_cache, self._state))
        t1 = time.perf_counter()
        self.stats["spec_draft_secs"] += t1 - t0
        with self._observe_dispatch("spec_verify", phase="decode",
                                    live_slots=live):
            if self.paged:
                toks, accepted, self._cache, self._state = \
                    self.engine._run_guarded(
                        self._verify_fn,
                        (self.engine._params, self._cache, self._state,
                         jnp.asarray(self._page_table), draft, sub))
            else:
                toks, accepted, self._cache, self._state = \
                    self.engine._run_guarded(
                        self._verify_fn,
                        (self.engine._params, self._cache, self._state,
                         draft, sub))
        self.stats["spec_verify_secs"] += time.perf_counter() - t1
        return ("spec", toks, accepted)

    # ------------------------------------------------------------------ #
    # Event processing (the host's lagging mirror of the device)
    # ------------------------------------------------------------------ #
    def _process_events(self, finished, keep=0):  # lock-held: _lock
        while len(self._events) > keep:
            inject.fire("serving.mirror_drain")
            ev = self._events.popleft()
            if ev[0] == "admit":
                self._process_admit(ev, finished)
            elif ev[0] == "spec":
                self._process_spec(ev, finished)
            else:
                self._process_decode(ev, finished)

    def _process_admit(self, ev, finished):  # lock-held: _lock
        _, req, slot, lane, first_dev, draft_lane = ev
        t0 = time.perf_counter()
        first = int(np.asarray(first_dev))
        self.stats["sync_secs"] += time.perf_counter() - t0
        self._lane_pool.give_back(lane)
        if self.speculative and draft_lane is not None:
            self._draft_lanes.give_back(draft_lane)
        if req.status in TERMINAL_STATUSES:
            # shed/cancelled while the admit event was in flight: free
            # the slot now (the shed path left it to us), discard the
            # token — the device lane stays a masked no-op until its
            # next occupant's admit overwrites it
            self._slots[slot] = None
            self._free.append(int(slot))
            self._release_slot_pages(slot)
            return
        if req.first_tok_t is None:
            req.first_tok_t = time.monotonic()
        if self._tracer is not None and req.t_first_tok is None \
                and req.t_trace is not None:
            # the first token is PROCESSED here (the host-mirror drain
            # point, one event behind the device) — TTFT is stamped
            # exactly once, on the tracer's clock; TokenStream replays
            # re-read req.tokens, they never come back through here
            req.t_first_tok = req.t_last_tok = self._tracer.now()
            self._hist.ttft.observe(req.t_first_tok - req.t_trace)
        req.tokens = list(req.prefix) + [first]
        if self._fairness is not None:
            # the sampled first token; prefill tokens (incl. any resumed
            # prefix) were charged when admission started
            self._fairness.charge(req.client_id, 1)
        # mirror the admit program's activation rule (the device saw the
        # REMAINING budget max_new - len(prefix))
        dev_new = req.max_new - len(req.prefix)
        if (req.eos >= 0 and first == req.eos) or dev_new == 1:
            self._slots[slot] = None
            self._free.append(int(slot))
            self._release_slot_pages(slot)
            finished[req.rid] = self._finalize(req)
        else:
            self._mirror_active[slot] = True
            self._publish_progress(req)

    def _mirror_commit_token(self, s, req, tok, finished):  # lock-held: _lock
        """The ONE per-token mirror rule both decode paths (plain block
        and speculative window) share: append the committed token,
        account it, and either retire the slot (eos or budget exhausted
        — mirroring the in-program rule) or flush the per-token stream
        at this drain point (the stream's tick — one event behind the
        device, TTFT/time-between-tokens observable here).  Returns
        True when the slot retired."""
        req.tokens.append(tok)
        self.stats["decode_tokens"] += 1
        if self._tracer is not None and req.t_trace is not None:
            # time-between-tokens at the drain point, stamped once per
            # token — late-attached stream replays never re-stamp
            now = self._tracer.now()
            if req.t_last_tok is not None:
                self._hist.tbt.observe(now - req.t_last_tok)
            req.t_last_tok = now
        if self._fairness is not None:
            self._fairness.charge(req.client_id, 1)
        if (req.eos >= 0 and tok == req.eos) \
                or len(req.tokens) >= req.max_new:
            self._mirror_active[s] = False
            self._slots[s] = None
            self._free.append(int(s))
            self._release_slot_pages(s)
            finished[req.rid] = self._finalize(req)
            return True
        self._publish_progress(req)
        return False

    def _process_decode(self, ev, finished):  # lock-held: _lock
        t0c = self._tracer.now() if self._tracer is not None else None
        n0 = self.stats["decode_tokens"]
        t0 = time.perf_counter()
        toks = np.asarray(ev[1])                         # [block, N]
        self.stats["sync_secs"] += time.perf_counter() - t0
        # mirror the in-program retirement rule step by step: an emitted
        # eos (or max_new reached) ends the request and frees its slot
        for t in range(toks.shape[0]):
            row = toks[t]
            for s in np.nonzero(self._mirror_active)[0]:
                req = self._slots[s]
                self._mirror_commit_token(s, req, int(row[s]), finished)
        committed = self.stats["decode_tokens"] - n0
        if self._tracer is not None:
            self._tracer.add("commit", "mirror", t0c, self._tracer.now(),
                             track="scheduler", kind="decode",
                             tokens=committed)
        if self._flightrec is not None:
            self._flightrec.record("commit", kind="decode",
                                   tokens=committed)
        self.occupancy_trace.append(
            (self._it, int(self._mirror_active.sum())))

    def _process_spec(self, ev, finished):  # lock-held: _lock
        """Mirror one speculative round: per live slot, append EXACTLY
        the ``accepted[s]`` committed tokens (the device's in-program
        accept count — rows beyond it are window padding, never real
        tokens) and apply the same per-token eos/max_new retirement rule
        the plain decode mirror applies.  Each committed token is pushed
        to the request's stream subscribers individually at this drain
        point, so a dispatch that commits m tokens emits m ORDERED
        per-token events with monotonic indices — never one blob per
        dispatch — and mid-window retirement cuts the stream exactly at
        the terminal token."""
        _, toks_dev, acc_dev = ev
        t0c = self._tracer.now() if self._tracer is not None else None
        n0 = self.stats["spec_committed_tokens"]
        t0 = time.perf_counter()
        toks = np.asarray(toks_dev)                      # [spec_k+1, N]
        acc = np.asarray(acc_dev)                        # [N]
        self.stats["sync_secs"] += time.perf_counter() - t0
        self.stats["spec_rounds"] += 1
        for s in np.nonzero(self._mirror_active)[0]:
            req = self._slots[s]
            m = int(acc[s])
            self.stats["spec_windows"] += 1
            self.stats["spec_committed_tokens"] += m
            for i in range(m):
                # by the in-program commit rule the device stopped
                # committing at exactly the token that retires here
                if self._mirror_commit_token(s, req, int(toks[i, s]),
                                             finished):
                    break
        # derived rates for /metrics + Serving/spec_* monitor events
        w = self.stats["spec_windows"]
        if w:
            committed = self.stats["spec_committed_tokens"]
            self.stats["spec_accept_rate"] = \
                (committed - w) / (w * self.spec_k)
            self.stats["spec_tokens_per_dispatch"] = \
                committed / self.stats["spec_rounds"]
        d, v = self.stats["spec_draft_secs"], self.stats["spec_verify_secs"]
        if d + v > 0:
            self.stats["spec_draft_fraction"] = d / (d + v)
        committed = self.stats["spec_committed_tokens"] - n0
        if self._tracer is not None:
            self._tracer.add("commit", "mirror", t0c, self._tracer.now(),
                             track="scheduler", kind="spec",
                             tokens=committed)
        if self._flightrec is not None:
            self._flightrec.record("commit", kind="spec",
                                   tokens=committed)
        self.occupancy_trace.append(
            (self._it, int(self._mirror_active.sum())))

    def _finalize(self, req):  # lock-held: _lock
        """The ``generate()`` output contract: ``[prompt..., tokens...]``
        of length ``P + max_new_tokens``, eos-padded past an early stop.
        For resumed requests ``tokens`` already includes the prefix, so
        the stitched output is exactly the uninterrupted run's."""
        req.finished_it = self._it
        req.status = RequestStatus.COMPLETED
        self.stats["completed"] += 1
        P = len(req.ids)
        pad = req.eos if req.eos >= 0 else 0
        out = np.full((P + req.max_new,), pad, np.int32)
        out[:P] = req.ids
        out[P:P + len(req.tokens)] = np.asarray(req.tokens, np.int32)
        ttft = (req.first_tok_t - req.submit_t) \
            if req.first_tok_t is not None else None
        self._results[req.rid] = RequestResult(
            rid=req.rid, status=RequestStatus.COMPLETED, output=out,
            client_id=req.client_id, submitted_it=req.submitted_it,
            finished_it=self._it, ttft_s=ttft,
            **self._trace_done(req, RequestStatus.COMPLETED))
        if self._flightrec is not None:
            self._flightrec.record("terminal", rid=req.rid,
                                   status=RequestStatus.COMPLETED,
                                   tokens=len(req.tokens))
        self._publish_end(req, RequestStatus.COMPLETED)
        return out

    # ------------------------------------------------------------------ #
    # Graceful preemption: drain -> crash-atomic snapshot -> resume
    # ------------------------------------------------------------------ #
    def _undrained_requests(self):  # lock-held: _lock
        """Every request that would be lost if the process died now:
        in-slot (non-terminal), mid-admission, and queued — in a stable
        order (slots, pending, queue)."""
        reqs = [r for r in self._slots
                if r is not None and r.status not in TERMINAL_STATUSES]
        if self._pending is not None \
                and self._pending.req.status not in TERMINAL_STATUSES:
            reqs.append(self._pending.req)
        reqs.extend(r for r in self._queue
                    if r.status not in TERMINAL_STATUSES)
        return reqs

    def preempt(self, checkpoint_dir, drain_budget_s=None, tag=None):
        """The SIGTERM path (``DSElasticAgent`` preemption): stop
        admission, keep decoding the in-flight slots for up to
        ``drain_budget_s`` seconds (default: the config's
        ``drain_budget_s``; 0 = snapshot immediately), then snapshot
        every undrained request — prompt, tokens generated so far,
        remaining deadline and the scheduler RNG lane state — through the
        crash-atomic checkpoint protocol, and retire the engine (it is
        closed afterwards).  Returns ``(tag, snapshotted_rids,
        finished)`` where ``finished`` holds the requests that completed
        during the drain.  A restarted server picks the snapshot up with
        :meth:`restore`; greedy resumed outputs are bitwise-identical to
        an uninterrupted run."""
        self._check_owner("preempt()")
        with self._lock:
            return self._preempt_locked(checkpoint_dir, drain_budget_s,
                                        tag)

    def _preempt_locked(self, checkpoint_dir, drain_budget_s, tag):  # lock-held: _lock
        if self._closed:
            raise RuntimeError("preempt() on a closed ServingEngine")
        budget = self.config.drain_budget_s if drain_budget_s is None \
            else float(drain_budget_s)
        t0 = time.monotonic()
        finished = {}
        self._shed_expired()
        # drain: decode-only iterations (no admissions) under the budget
        while (self._mirror_active.any()
               or any(e[0] == "admit" for e in self._events)) \
                and time.monotonic() - t0 < budget:
            inject.fire("serving.mid_drain")
            try:
                dispatched = self._dispatch_decode()
            except Exception as e:
                # a sick device must not block the snapshot: the failed
                # dispatch aborted the in-flight slots (their requests
                # are ABORTED with the reason); snapshot what remains
                logger.error(f"serving preempt: drain dispatch failed "
                             f"({type(e).__name__}: {e}) — snapshotting "
                             f"the queue")
                break
            self._process_events(finished, keep=1 if dispatched else 0)
        try:
            self._process_events(finished, keep=0)
        except Exception as e:
            logger.warning(f"serving preempt: discarding unreadable "
                           f"in-flight events ({type(e).__name__}: {e})")
            self._abort_in_flight("preempt event flush failed")
        drain_secs = time.monotonic() - t0
        self._shed_expired()                 # don't snapshot expired work
        undrained = self._undrained_requests()
        tag = self.snapshot(checkpoint_dir, tag=tag)
        for req in undrained:
            req.status = RequestStatus.PREEMPTED
            # active HTTP/token streams end with the TYPED event — the
            # client knows its request resumes on a restarted server
            # (reconnect and re-subscribe) instead of seeing a dead
            # socket with no verdict
            self._publish_end(req, RequestStatus.PREEMPTED,
                              f"preempted — snapshotted for resume "
                              f"(tag {tag!r})")
        snapped = [r.rid for r in undrained]
        # retire the engine without ABORTED accounting: the snapshotted
        # requests are not lost, they resume elsewhere
        if self._pending is not None:
            self._give_back_lanes(self._pending)
            self._pending = None
        self._queue.clear()
        self._events.clear()
        self._slots = [None] * self.num_slots
        self._free = deque(range(self.num_slots))
        self._mirror_active[:] = False
        if self._cache is not None:
            if self.paged:
                self._pool_ws.give_back(self._cache)
            else:
                self._cache_ws.give_back(self._cache)
            self._cache = None
        self._state = None
        self._cache_ws.release()
        self._lane_pool.release()
        self._release_draft_workspaces()
        self._paging_reset()
        if self.paged:
            self._pool_ws.release()
        self._detach_observability()
        self._closed = True
        self._close_report = sorted(snapped)
        self._cond.notify_all()
        self.wake.set()
        self.stats["drain_secs"] = \
            self.stats.get("drain_secs", 0.0) + drain_secs
        self.stats["preempt_snapshotted"] = len(snapped)
        if self._pending_reports:
            finished.update(self._pending_reports)
            self._pending_reports.clear()
        logger.warning(f"serving preempt: drained {drain_secs:.2f}s, "
                       f"{len(finished)} request(s) finished in drain, "
                       f"{len(snapped)} snapshotted to {tag!r}")
        return tag, snapped, finished

    def snapshot(self, checkpoint_dir, tag=None):
        """Crash-atomically publish the undrained requests (and the
        scheduler RNG lane state) under ``checkpoint_dir`` — the
        serving analog of a training checkpoint (staging dir, manifest
        with checksums, fsync, atomic rename, ``latest`` swap; see
        ``inference/serving/snapshot.py``).  Pure write: the engine's
        bookkeeping is untouched.  Returns the tag.  Thread-safe (the
        state walk runs under the engine lock; ``preempt()`` re-enters
        it lock-held)."""
        with self._lock:
            return self._snapshot_locked(checkpoint_dir, tag)

    def _snapshot_locked(self, checkpoint_dir, tag):  # lock-held: _lock
        from deepspeed_tpu.inference.serving.snapshot import save_snapshot
        self._snap_seq += 1
        tag = tag or f"serving_{self._snap_seq}"
        import json
        now = time.monotonic()
        reqs = []
        for r in self._undrained_requests():
            cid = r.client_id
            try:
                json.dumps(cid)
            except (TypeError, ValueError):
                # a non-JSON client_id must never cost the snapshot (and
                # with it every undrained request) on the SIGTERM path
                logger.warning(
                    f"serving snapshot: request {r.rid} client_id "
                    f"{type(cid).__name__} is not JSON-serializable — "
                    f"stored as str()")
                cid = str(cid)
            entry = {
                "rid": int(r.rid),
                "client_id": cid,
                "prompt": [int(t) for t in r.ids],
                # tokens generated so far (a queued resumed request has
                # produced none this incarnation — carry its prefix)
                "tokens": [int(t) for t in (r.tokens or r.prefix)],
                "max_new": int(r.max_new),
                "eos": int(r.eos),
                "deadline_remaining_s":
                    None if r.deadline is None else r.deadline - now,
                "submitted_it": int(r.submitted_it),
                "priority": int(r.priority),
            }
            if self.paged and r.slot is not None \
                    and int(r.slot) in self._slot_pages:
                # diagnostics only (restore re-prefills; physical pages
                # are meaningless in another process) — range-compressed,
                # never one JSON int per table entry
                entry["pages"] = compact_page_str(
                    self._slot_pages[int(r.slot)])
            reqs.append(entry)
        fcfg = getattr(self.engine._config, "fault", None)
        state = {
            "seq": int(self._snap_seq),
            "iteration": int(self._it),
            "next_rid": int(self._next_rid),
            "rng": np.asarray(
                jax.random.key_data(self._rng)).ravel().tolist(),
            "requests": reqs,
        }
        if self._fairness is not None:
            # quota balances survive preemption: a restarted server keeps
            # enforcing the same per-client budgets (conservative — decay
            # during the downtime is not credited; frontend/fairness.py)
            state["fairness"] = self._fairness.state_dict()
        return save_snapshot(
            checkpoint_dir, tag, state,
            checksum=getattr(fcfg, "checksum", None) or "sha256")

    def restore(self, checkpoint_dir):
        """Resume the newest valid snapshot's requests into this server's
        queue, keeping their original request ids, client ids and
        remaining deadlines; the RNG lane state is restored too.  Each
        resumed request re-prefills ``prompt + generated-so-far`` through
        the ordinary admission path and decodes only its remaining budget
        — under greedy decoding the stitched output is bitwise what the
        uninterrupted run would have produced.  Returns the restored
        request ids (empty when there is nothing to resume)."""
        from deepspeed_tpu.inference.serving.snapshot import \
            load_newest_snapshot
        tag, state = load_newest_snapshot(checkpoint_dir)
        if state is None:
            return []
        with self._lock:
            rids = self._restore_locked(tag, state)
        self.wake.set()                  # rouse an idle scheduler thread
        return rids

    def _restore_locked(self, tag, state):  # lock-held: _lock
        self._snap_seq = max(self._snap_seq, int(state.get("seq", 0)))
        if self._fairness is not None and state.get("fairness"):
            self._fairness.load_state(state["fairness"])
        if state.get("rng"):
            self._rng = jax.random.wrap_key_data(
                jnp.asarray(state["rng"], jnp.uint32))
        now = time.monotonic()
        rids = []
        for r in state.get("requests", []):
            if int(r["rid"]) in self._requests:
                raise ValueError(
                    f"restore(): request id {r['rid']} already exists on "
                    f"this server — call restore() before submitting new "
                    f"work (snapshotted ids are preserved verbatim)")
            ids = np.asarray(r["prompt"], np.int32)
            prefix = [int(t) for t in r.get("tokens", [])]
            max_new, eos = int(r["max_new"]), int(r["eos"])
            if len(prefix) >= max_new \
                    or (eos >= 0 and eos in prefix):
                # defensive: a finished request has nothing to resume
                continue
            deadline = None
            if r.get("deadline_remaining_s") is not None:
                deadline = now + float(r["deadline_remaining_s"])
            req = ServeRequest(
                int(r["rid"]), ids, max_new, eos, submitted_it=self._it,
                deadline=deadline, client_id=r.get("client_id"),
                prefix=prefix, submit_t=now, resumed=True,
                # clamp to THIS server's lane count (the snapshot may
                # come from a config with more lanes); aging restarts
                # from restore time — conservative, never a starvation
                priority=min(int(r.get("priority", 0)),
                             self.priority_lanes - 1))
            # every restored request must pass submit()'s capacity check
            # against THIS server's lane config (the snapshot may come
            # from a server with a larger max_cache_len / smaller chunk
            # — admitting an oversized request would stream prefill
            # chunks past the lane's end)
            P = len(ids)
            spec_tail = (self.spec_k - 1) if self.speculative else 0
            need = max(P + max_new + spec_tail,
                       -(-P // self.chunk) * self.chunk)
            if need > self.cache_len:
                self._requests[req.rid] = req
                self._record_terminal(
                    req, RequestStatus.ABORTED,
                    f"restored request needs more than the "
                    f"{self.cache_len} cache positions this server's "
                    f"lanes hold (prompt {P} + new {max_new}) — raise "
                    f"serving.max_cache_len to resume it")
                logger.warning(f"serving restore: request {req.rid} does "
                               f"not fit this server's lanes — ABORTED")
                self._next_rid = max(self._next_rid, req.rid + 1)
                continue
            if self.paged and pages_for(need, self.page) \
                    > self._pool.allocatable:
                # the snapshot may come from a server with a bigger page
                # pool — mirror submit()'s pool-capacity check instead
                # of stalling admission forever on an unfittable request
                self._requests[req.rid] = req
                self._record_terminal(
                    req, RequestStatus.ABORTED,
                    f"restored request needs "
                    f"{pages_for(need, self.page)} pages but this "
                    f"server's pool holds {self._pool.allocatable} "
                    f"allocatable (num_pages={self.num_pages} incl. "
                    f"trash) — raise serving.num_pages to resume it")
                logger.warning(f"serving restore: request {req.rid} does "
                               f"not fit this server's page pool — "
                               f"ABORTED")
                self._next_rid = max(self._next_rid, req.rid + 1)
                continue
            # the resumed fill (prompt + prefix) must still fit a lane;
            # when the chunk-padded tail would overflow, drop the prefix
            # and re-decode from scratch — still bitwise-correct, just
            # wasteful
            fill = P + len(prefix)
            padded = -(-fill // self.chunk) * self.chunk
            if prefix and max(fill + (max_new - len(prefix)) + spec_tail,
                              padded) > self.cache_len:
                logger.warning(
                    f"serving restore: request {req.rid} prefix "
                    f"({len(prefix)} tokens) does not fit its lane "
                    f"chunk-padded — re-decoding from the prompt")
                req.prefix = []
            if self._tracer is not None:
                # the resumed incarnation's span tree starts at restore
                req.t_trace = self._tracer.now()
            self._queue.append(req)
            self._requests[req.rid] = req
            self._next_rid = max(self._next_rid, req.rid + 1)
            rids.append(req.rid)
        self._next_rid = max(self._next_rid,
                             int(state.get("next_rid", 0)))
        self.stats["resumed"] += len(rids)
        if rids:
            log_dist(f"serving restore[{tag}]: resumed {len(rids)} "
                     f"request(s) {rids}", ranks=[0])
        return rids

    # ------------------------------------------------------------------ #
    # Plumbing
    # ------------------------------------------------------------------ #
    def _ensure_workspace(self):  # lock-held: _lock
        if self._cache is None:
            if self.paged:
                self._cache = self._pool_ws.take(
                    self.num_pages, self.page, self.engine.compute_dtype)
                # fresh (or reallocated) pool buffer: the host mirror
                # must match it — everything free, nothing indexed
                self._paging_reset()
            else:
                self._cache = self._cache_ws.take(
                    self.num_slots, self.cache_len,
                    self.engine.compute_dtype)
        if self.speculative and self._draft_cache is None:
            self._draft_cache = self._draft_ws.take(
                self.num_slots, self.cache_len, self.engine.compute_dtype)
        if self._state is None:
            self._state = {k: jnp.asarray(v) for k, v in
                           init_slot_state(self.num_slots).items()}
            self._mirror_active[:] = False

    def _emit_metrics(self):  # lock-held: _lock
        mon = self.monitor
        if mon is None or not getattr(mon, "enabled", True):
            return
        wall = self.stats["wall_secs"]
        mon.write_events([
            ("Serving/queue_depth", self.queue_depth, self._it),
            ("Serving/slot_occupancy",
             self.active_slots / self.num_slots, self._it),
            ("Serving/decode_tok_s",
             self.stats["decode_tokens"] / wall if wall > 0 else 0.0,
             self._it),
            ("Serving/prefill_decode_ratio",
             self.stats["prefill_tokens"]
             / max(self.stats["decode_tokens"], 1), self._it),
            ("Serving/completed", self.stats["completed"], self._it),
            ("Serving/shed", self.stats["shed"], self._it),
            ("Serving/cancelled", self.stats["cancelled"], self._it),
            ("Serving/aborted", self.stats.get("aborted", 0), self._it),
            ("Serving/breaker_open",
             1.0 if self._breaker.open else 0.0, self._it),
            ("Serving/lock_wait_scheduler_s",
             self.stats["lock_wait_scheduler_s"], self._it),
            ("Serving/lock_wait_handler_s",
             self.stats["lock_wait_handler_s"], self._it),
        ] + ([
            ("Serving/fairness_rejected",
             self.stats["fairness_rejected"], self._it),
        ] if self._fairness is not None else []) + ([
            ("Serving/page_pool_util", self.page_pool_utilization,
             self._it),
            ("Serving/prefix_hit_rate", self.prefix_hit_rate, self._it),
        ] if self.paged else []) + ([
            ("Serving/hbm_bytes_in_use",
             self.stats["hbm_bytes_in_use"], self._it),
            ("Serving/hbm_peak_bytes",
             self.stats["hbm_peak_bytes"], self._it),
            ("Serving/hbm_unattributed_bytes",
             self.stats["hbm_unattributed_bytes"], self._it),
        ] if self._memwatch is not None else []) + ([
            ("Serving/spec_accept_rate",
             self.stats["spec_accept_rate"], self._it),
            ("Serving/spec_tokens_per_dispatch",
             self.stats["spec_tokens_per_dispatch"], self._it),
            ("Serving/spec_draft_fraction",
             self.stats["spec_draft_fraction"], self._it),
        ] if self.speculative else []))
