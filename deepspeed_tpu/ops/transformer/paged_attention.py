"""Pallas paged attention — decode & chunked prefill over block-table pools.

TPU-native analog of vLLM's PagedAttention kernel: the KV cache is a shared
page pool ``[L, num_pages, page_size, KVH*D]`` and each batch row owns a
block table ``pages[b, virtual_page] -> physical_page``.  Before this
kernel, the paged serving path materialized a per-layer virtual view with
``take_along_axis`` (``models/transformer._paged_gather``) and ran dense
attention over it — one full gathered cache copy per layer per step, which
is the BENCH_r04 bs128 decode cliff (8,673 → 1,193 tok/s/chip).

Design: the monolithic decode/chunk kernels in ``decode_attention.py`` are
already split-K online-softmax kernels whose grid walks KV blocks of one
batch row in order, with the block location resolved by a BlockSpec index
map from scalar-prefetch operands.  A paged cache is the SAME computation
with a different address map: virtual page ``ik`` of row ``b`` lives at
pool page ``pages[b, ik]``.  So this module reuses the kernel BODIES
(``_decode_kernel`` / ``_chunk_prefill_kernel``) unchanged — online
softmax with cross-page max/sum merge, block-diagonal Q, int8-KV dequant
fused onto the score/probability tiles, fused aliased cache write — and
only swaps the index maps:

* ``block_k = page_size`` and the grid's KV dimension walks VIRTUAL pages
  in order, so the kernels' virtual position math (``pos = ik*block_k +
  iota``, length masks, write row ``(length-1) % block_k``) transfers
  verbatim.
* The page table rides as a THIRD scalar-prefetch operand; input index
  maps resolve ``(layer, pages[b, virt], 0, 0)``.  Pages past the live
  region pin to the last live page — Mosaic elides the repeated-index
  DMA, so dead-tail grid steps fetch nothing (split-K cost is
  O(ceil(length/page_size)) pages, not O(table width)).
* The fused decode write targets the pool through the table too: the
  aliased output's 8-row write stripe pins to ``(layer,
  pages[b, (len-1)//page], ((len-1)%page)//8, 0)``.  Dead lanes (length
  0, table redirected to the reserved trash page 0 by the caller) write
  their garbage stripe into the trash page — the paged analog of the
  monolithic "dead lanes write into their own lane" safety argument.

Numerics: with ``block_k = page_size`` the online-softmax block sequence
is identical to ``decode_attention(block_k=page_size)`` over the gathered
virtual view, so the two are BITWISE equal (regression-tested in
tests/unit/test_paged_attention.py); greedy serving outputs stay bitwise
equal to the monolithic engine as before.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.transformer.decode_attention import (
    _chunk_prefill_kernel, _decode_kernel)
from deepspeed_tpu.ops.transformer.flash_attention import LSE_LANES, _interpret
from deepspeed_tpu.utils.jax_compat import CompilerParams as _CompilerParams


def _paged_decode_body(len_ref, layer_ref, pages_ref, *args, **kw):
    # the page table is consumed entirely by the BlockSpec index maps;
    # the kernel body is the monolithic decode kernel, verbatim
    del pages_ref
    _decode_kernel(len_ref, layer_ref, *args, **kw)


def _paged_chunk_body(start_ref, layer_ref, pages_ref, *args, **kw):
    del pages_ref
    _chunk_prefill_kernel(start_ref, layer_ref, *args, **kw)


def _pool_dims(q, k_pool):
    if k_pool.ndim != 4:
        raise ValueError(
            f"paged attention expects a layer-stacked pool "
            f"[L, num_pages, page_size, KVH*D]; got shape {k_pool.shape}")
    D = q.shape[-1]
    page, KVHD = k_pool.shape[-2], k_pool.shape[-1]
    KVH = KVHD // D
    return page, KVHD, KVH


def paged_decode_attention(q, k_pool, v_pool, lengths, pages, *, scale=None,
                           layer=None, k_scale=None, v_scale=None,
                           int8_matmuls=False, new_k=None, new_v=None):
    """Single-token decode attention over a paged KV pool.

    q: [B, H, D]; pools: [L, num_pages, page_size, KVH*D] (the
    ``init_paged_cache`` layout — page-major S-major slabs, heads
    flattened into lanes, so each page is one contiguous full-lane-width
    DMA).  ``pages``: [B, n_virtual_pages] int32 block tables (virtual
    page ``pos // page_size`` → physical pool page; dead/unmapped rows
    must point at the reserved trash page 0).  ``lengths``: [B] int32 —
    valid virtual positions INCLUDING this step's token.  ``layer``: the
    (traced) layer index into the stacked pools.  Returns [B, H, D].

    ``k_scale``/``v_scale`` ([L, num_pages, page_size, KVH]) switch the
    pools to int8 payloads with per-(position, kv-head) dequant scales,
    applied to score/probability tiles exactly as in
    :func:`~deepspeed_tpu.ops.transformer.decode_attention.decode_attention`.

    ``new_k``/``new_v`` ([B, KVH, D]) switch on the FUSED CACHE WRITE:
    the kernel quantizes (when the pool is int8) and writes this step's
    row at virtual position ``lengths[b]-1`` THROUGH the block table
    into the pool, returned as aliased outputs — the caller must then
    NOT pre-scatter the row.  Requires ``page_size % 8 == 0`` (the
    8-sublane-aligned write stripe) and is unsupported with
    ``int8_matmuls`` (same restriction as the monolithic kernel).
    Returns ``(out, k_pool, v_pool[, k_scale, v_scale])`` instead of
    ``out``.
    """
    B, H, D = q.shape
    page, KVHD, KVH = _pool_dims(q, k_pool)
    G = H // KVH
    if layer is None:
        raise ValueError("layer-stacked pools require layer=")
    quant = k_scale is not None
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    if int8_matmuls and not quant:
        raise ValueError("int8_matmuls requires quantized pools "
                         "(k_scale/v_scale)")
    fused_write = new_k is not None
    if (new_k is None) != (new_v is None):
        raise ValueError("new_k and new_v must be given together")
    if fused_write and int8_matmuls:
        raise ValueError("int8_matmuls is unsupported with the fused "
                         "cache write (new_k/new_v)")
    if fused_write and page % 8 != 0:
        raise ValueError(
            f"fused paged write needs page_size % 8 == 0 (8-sublane-"
            f"aligned write stripes); got {page}")
    mxu_int8 = bool(int8_matmuls)
    if scale is None:
        scale = 1.0 / float(np.sqrt(D))
    nk = pages.shape[1]                     # virtual pages per row
    layer_arr = jnp.asarray([layer], jnp.int32)
    pages_arr = jnp.asarray(pages, jnp.int32)

    def _live_page(ik, lens, b):
        # pin virtual pages past the live region to the LAST live page:
        # its physical index then repeats across the dead tail and Mosaic
        # elides the DMA (compute is pl.when-gated off in the body)
        last = jnp.maximum((lens[b] + page - 1) // page - 1, 0)
        return jnp.minimum(ik, last)

    kv_spec = pl.BlockSpec(
        (1, 1, page, KVHD),
        lambda b, ik, lens, li, pg: (li[0], pg[b, _live_page(ik, lens, b)],
                                     0, 0))
    sc_spec = pl.BlockSpec(
        (1, 1, page, KVH),
        lambda b, ik, lens, li, pg: (li[0], pg[b, _live_page(ik, lens, b)],
                                     0, 0))

    in_specs = [
        pl.BlockSpec((1, H, D), lambda b, ik, lens, li, pg: (b, 0, 0)),
        kv_spec,
        kv_spec,
    ]
    operands = [q, k_pool, v_pool]
    if quant:
        in_specs += [sc_spec, sc_spec]
        operands += [k_scale, v_scale]

    out_specs = [pl.BlockSpec((1, H, D),
                              lambda b, ik, lens, li, pg: (b, 0, 0))]
    out_shape = [jax.ShapeDtypeStruct((B, H, D), q.dtype)]
    io_aliases = {}
    if fused_write:
        # table-resolved write stripe: virtual write position lens[b]-1
        # lands on pool page pages[b, (lens[b]-1)//page] at in-page row
        # (lens[b]-1) % page; the output block covers only that row's
        # 8-sublane-aligned stripe (index in 8-row units), constant per
        # batch row, so Mosaic flushes 8 rows once after the final grid
        # step — same stripe economics as the monolithic fused write
        def _wpage(lens, pg, b):
            return pg[b, jnp.maximum(lens[b] - 1, 0) // page]

        def _wstripe(lens, b):
            return (jnp.maximum(lens[b] - 1, 0) % page) // 8

        kvo_spec = pl.BlockSpec(
            (1, 1, 8, KVHD),
            lambda b, ik, lens, li, pg: (li[0], _wpage(lens, pg, b),
                                         _wstripe(lens, b), 0))
        sco_spec = pl.BlockSpec(
            (1, 1, 8, KVH),
            lambda b, ik, lens, li, pg: (li[0], _wpage(lens, pg, b),
                                         _wstripe(lens, b), 0))
        nspec = pl.BlockSpec((1, KVH, D),
                             lambda b, ik, lens, li, pg: (b, 0, 0))
        in_specs += [nspec, nspec]
        operands += [new_k, new_v]
        out_specs += [kvo_spec, kvo_spec]
        out_shape += [jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
                      jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype)]
        # operand indices INCLUDE the three scalar-prefetch args
        io_aliases = {4: 1, 5: 2}
        if quant:
            out_specs += [sco_spec, sco_spec]
            out_shape += [jax.ShapeDtypeStruct(k_scale.shape, k_scale.dtype),
                          jax.ShapeDtypeStruct(v_scale.shape, v_scale.dtype)]
            io_aliases = {4: 1, 5: 2, 6: 3, 7: 4}

    res = pl.pallas_call(
        functools.partial(_paged_decode_body, scale=float(scale),
                          block_k=page, nk=nk, kvh=KVH, g=G, d=D,
                          stacked=True, quant=quant, window=None,
                          mxu_int8=mxu_int8, fused_write=fused_write),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(B, nk),
            in_specs=in_specs,
            out_specs=out_specs if fused_write else out_specs[0],
            scratch_shapes=[
                pltpu.VMEM((H, LSE_LANES), jnp.float32),
                pltpu.VMEM((H, LSE_LANES), jnp.float32),
                pltpu.VMEM((H, D), jnp.float32),
                pltpu.VMEM((H, KVHD),
                           jnp.int8 if mxu_int8 else q.dtype),
            ] + ([pltpu.VMEM((H, LSE_LANES), jnp.float32)]
                 if mxu_int8 else [])),
        out_shape=out_shape if fused_write else out_shape[0],
        input_output_aliases=io_aliases,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
            # pages are small (<= a monolithic block_k) — the monolithic
            # slab-sized floor is comfortably enough headroom
            vmem_limit_bytes=max(
                96 * 1024 * 1024,
                6 * page * KVHD * q.dtype.itemsize + 16 * 1024 * 1024)),
        interpret=_interpret(),
    )(jnp.asarray(lengths, jnp.int32), layer_arr, pages_arr, *operands)
    return res


def paged_chunk_prefill_attention(q, k_pool, v_pool, starts, pages, *,
                                  scale=None, layer=None, k_scale=None,
                                  v_scale=None):
    """Chunked-prefill attention over a paged KV pool: a block of C fresh
    query tokens (already scattered into the pool at virtual positions
    ``starts[b] .. starts[b]+C-1``) attends causally over each row's
    paged cache.  Same [C, page_size] score-tile economics as
    :func:`~deepspeed_tpu.ops.transformer.decode_attention.chunk_prefill_attention`
    — paged admission prefill never materializes the gathered virtual
    view (previously one ``take_along_axis`` pool copy per layer per
    chunk).

    q: [B, C, H, D]; pools/pages/layer as in
    :func:`paged_decode_attention`.  starts: [B] int32 per-row chunk
    start (query row ``iq`` masks virtual positions ``> starts[b]+iq``).
    Returns [B, C, H, D].
    """
    B, C, H, D = q.shape
    page, KVHD, KVH = _pool_dims(q, k_pool)
    G = H // KVH
    if layer is None:
        raise ValueError("layer-stacked pools require layer=")
    quant = k_scale is not None
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    if scale is None:
        scale = 1.0 / float(np.sqrt(D))
    nk = pages.shape[1]
    layer_arr = jnp.asarray([layer], jnp.int32)
    pages_arr = jnp.asarray(pages, jnp.int32)

    def _live_page(ik, st, b):
        # the chunk's furthest reachable virtual position is st[b]+C-1
        last = jnp.maximum((st[b] + C + page - 1) // page - 1, 0)
        return jnp.minimum(ik, last)

    kv_spec = pl.BlockSpec(
        (1, 1, page, KVHD),
        lambda b, ik, st, li, pg: (li[0], pg[b, _live_page(ik, st, b)],
                                   0, 0))
    sc_spec = pl.BlockSpec(
        (1, 1, page, KVH),
        lambda b, ik, st, li, pg: (li[0], pg[b, _live_page(ik, st, b)],
                                   0, 0))

    in_specs = [
        pl.BlockSpec((1, C, H * D), lambda b, ik, st, li, pg: (b, 0, 0)),
        kv_spec,
        kv_spec,
    ]
    operands = [q.reshape(B, C, H * D), k_pool, v_pool]
    if quant:
        in_specs += [sc_spec, sc_spec]
        operands += [k_scale, v_scale]

    out = pl.pallas_call(
        functools.partial(_paged_chunk_body, scale=float(scale),
                          block_k=page, nk=nk, c=C, kvh=KVH, g=G, d=D,
                          stacked=True, quant=quant),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(B, nk),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, C, H * D),
                                   lambda b, ik, st, li, pg: (b, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((C, H), jnp.float32),         # running max
                pltpu.VMEM((C, H), jnp.float32),         # running sum
                pltpu.VMEM((C, H * D), jnp.float32),     # per-head acc
            ]),
        out_shape=jax.ShapeDtypeStruct((B, C, H * D), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=max(
                64 * 1024 * 1024,
                4 * page * KVHD * q.dtype.itemsize
                + 2 * C * H * D * 4 + 16 * 1024 * 1024)),
        interpret=_interpret(),
    )(jnp.asarray(starts, jnp.int32), layer_arr, pages_arr, *operands)
    return out.reshape(B, C, H, D)
