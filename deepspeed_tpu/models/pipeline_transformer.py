"""Pipelined transformer — the ``GPT2ModelPipe`` pattern for this framework:
builds a ``PipelineModule`` from a ``TransformerConfig`` with single-tensor
layers (embed → blocks → norm+head) so the pipeline engine can split
pre/body/post and stack the uniform trunk."""

import jax
import jax.numpy as jnp
import flax.linen as nn

from deepspeed_tpu.models.transformer import (TransformerConfig, Attention, MLP,
                                              _norm, cross_entropy_loss)
from deepspeed_tpu.runtime.pipe.module import PipelineModule, LayerSpec


class EmbedPipe(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, input_ids):
        cfg = self.config
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, param_dtype=jnp.float32,
                     name="embed_tokens")(input_ids)
        if cfg.position_embedding == "learned":
            B, S = input_ids.shape
            pos = jnp.broadcast_to(jnp.arange(S), (B, S))
            x = x + nn.Embed(cfg.max_seq_len, cfg.hidden_size,
                             param_dtype=jnp.float32,
                             name="embed_positions")(pos)
        return x.astype(cfg.jnp_dtype)


class BlockPipe(nn.Module):
    """Single-tensor transformer block: positions recomputed from shape
    (the pipeline passes activations only, reference ``pipe/module.py``
    layers are single-tensor too)."""
    config: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        B, S, _ = x.shape
        positions = jnp.broadcast_to(jnp.arange(S), (B, S))
        attn, _ = Attention(cfg, name="attn")(
            _norm(cfg, "input_norm")(x).astype(cfg.jnp_dtype), positions, None)
        x = x + attn
        x = x + MLP(cfg, name="mlp")(
            _norm(cfg, "post_attn_norm")(x).astype(cfg.jnp_dtype))
        return x


class HeadPipe(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        x = _norm(cfg, "final_norm")(x).astype(cfg.jnp_dtype)
        return nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.jnp_dtype,
                        param_dtype=jnp.float32, name="lm_head")(x)


def lm_loss(logits, labels):
    return cross_entropy_loss(logits, labels)


def transformer_pipe(config: TransformerConfig, num_stages=None,
                     **pipe_kwargs) -> PipelineModule:
    # the single-tensor pipe layers implement the pre-LN trunk only;
    # reject configs they would silently mis-build
    unsupported = [n for n, bad in (
        ("pre_layer_norm=False", not config.pre_layer_norm),
        ("embed_proj_dim", config.embed_proj_dim is not None),
        ("moe_num_experts", config.moe_num_experts > 0),
        ("attention_layers", config.attention_layers is not None),
    ) if bad]
    if unsupported:
        raise NotImplementedError(
            f"transformer_pipe does not support {unsupported}; use the "
            "non-pipeline Transformer for these configs")
    layers = [LayerSpec(EmbedPipe, config)]
    layers += [LayerSpec(BlockPipe, config) for _ in range(config.num_layers)]
    layers += [LayerSpec(HeadPipe, config)]
    return PipelineModule(layers, num_stages=num_stages, loss_fn=lm_loss,
                          **pipe_kwargs)
