"""TL010 — implicit replication at mesh boundaries (sharding lint).

On a multi-chip mesh the default placement is FULL REPLICATION: an array
that enters a ``shard_map``/jit program without a ``PartitionSpec`` (or
with the explicit empty spec ``P()``) is materialized whole on every chip,
and anything downstream that needs it sharded pays an all-gather per step.
For weights that is a capacity bug; for activations — anything whose size
scales with batch or sequence — it is the classic "8-chip run turned into
an all-gather storm" regression the comm-cost contracts exist to catch.
This rule catches it at the SOURCE level, before a byte moves:

* a ``shard_map`` application (direct call, ``functools.partial``
  decorator, or the ``jax_compat`` alias) carrying a ``mesh=`` but missing
  ``in_specs``/``out_specs`` — every operand silently replicates;
* a ``jax.jit`` call inside a ``with <mesh>:`` block with no
  ``in_shardings``/``out_shardings`` at all — same default, harder to see;
* a bare ``P()`` spec bound to a parameter whose NAME says its size scales
  with batch or sequence (``batch``, ``input_ids``, ``hidden``, ``x`` …) —
  in ``in_specs`` (literal tuples or module-resolvable spec variables;
  outputs have no bindable name, so replicated ``out_specs`` surface
  through the comm budgets instead), or as
  ``device_put(x, NamedSharding(mesh, P()))`` /
  ``with_sharding_constraint(x, ... P())`` on a batch-scaling name.

Deliberate replication (a compressed-collective input that IS the full
local gradient, a pipeline region that slices the global batch in-program)
gets a suppression with the reason — the point is that every fully
replicated batch-scaling array in the package is either a bug or a
documented decision.
"""

import ast
import re

from deepspeed_tpu.tools.lint.core import Finding, dotted_name, rule

# names whose arrays scale with batch and/or sequence length — the ones a
# replicated placement turns into per-step all-gather traffic
_BATCH_SCALED_RE = re.compile(
    r"batch|input|label|ids|tok|seq|hid|act|logit|emb|cache|kv|lane|pool|"
    r"micro|prompt|ctx", re.IGNORECASE)
_BATCH_EXACT_RE = re.compile(r"^[xhqkv][s0-9]?$|^attn$|^out$")


def is_batch_scaled_name(name):
    if not name:
        return False
    leaf = name.split(".")[-1]
    return bool(_BATCH_SCALED_RE.search(leaf) or _BATCH_EXACT_RE.match(leaf))


def _callee_leaf(node):
    name = dotted_name(node)
    return name.split(".")[-1].lstrip("_") if name else None


def is_shard_map_callee(node):
    return _callee_leaf(node) == "shard_map"


def is_bare_partition_spec(node):
    """``P()`` / ``PartitionSpec()`` with no axes — the explicit
    fully-replicated spec."""
    return (isinstance(node, ast.Call)
            and _callee_leaf(node.func) in ("P", "PartitionSpec")
            and not node.args and not node.keywords)


def _positional_params(fn_node):
    a = fn_node.args
    return [p.arg for p in (*a.posonlyargs, *a.args)
            if p.arg not in ("self", "cls")]


def _resolve_name_assign(module, name, before_line):
    """The value of the lexically nearest ``name = <expr>`` assignment
    above ``before_line`` — how ``in_specs = (...)`` variables passed to a
    later shard_map call are resolved."""
    best = None
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name \
                and node.lineno <= before_line \
                and (best is None or node.lineno > best.lineno):
            best = node
    return best.value if best is not None else None


def _resolve_wrapped_params(module, fn_expr, before_line=None):
    """Positional parameter names of the callable a shard_map wraps, when
    module-locally resolvable (a local ``def`` or a lambda).  Several
    same-named defs (one ``region`` per plan builder) resolve to the
    lexically nearest one above the call."""
    if isinstance(fn_expr, ast.Lambda):
        return _positional_params(fn_expr)
    if isinstance(fn_expr, ast.Name):
        best = None
        for fn in module.functions:
            if fn.name != fn_expr.id:
                continue
            if before_line is not None and fn.node.lineno > before_line:
                continue
            if best is None or fn.node.lineno > best.node.lineno:
                best = fn
        if best is not None:
            return _positional_params(best.node)
    return None


def shard_map_applications(module):
    """Every shard_map application in the module as
    ``(line, col, kwargs: {name: expr}, wrapped_params or None)`` —
    direct calls ``shard_map(f, mesh=..., ...)``, and
    ``functools.partial(shard_map, ...)`` decorators whose specs bind to
    the decorated ``def``."""
    out = []
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call) and is_shard_map_callee(node.func):
            kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
            wrapped = node.args[0] if node.args else None
            out.append((node.lineno, node.col_offset, kwargs,
                        _resolve_wrapped_params(module, wrapped,
                                                node.lineno)))
    for fn in module.functions:
        for dec in getattr(fn.node, "decorator_list", []):
            if not (isinstance(dec, ast.Call)
                    and _callee_leaf(dec.func) == "partial"
                    and dec.args and is_shard_map_callee(dec.args[0])):
                continue
            kwargs = {kw.arg: kw.value for kw in dec.keywords if kw.arg}
            out.append((dec.lineno, dec.col_offset, kwargs,
                        _positional_params(fn.node)))
    return out


def spec_entries(module, spec_expr, call_line):
    """The per-argument entries of an ``in_specs``/``out_specs``
    expression, resolving one level of ``specs = (...)`` variable
    indirection.  Returns a list of AST nodes, or None when the structure
    is not statically visible (tree-mapped specs, call results)."""
    if isinstance(spec_expr, ast.Name):
        spec_expr = _resolve_name_assign(module, spec_expr.id, call_line)
    if spec_expr is None:
        return None
    if isinstance(spec_expr, (ast.Tuple, ast.List)):
        return list(spec_expr.elts)
    return [spec_expr]


def _mesh_with_blocks(module):
    """Line spans of ``with`` blocks whose context expression mentions a
    mesh (``with mesh:``, ``with self.mesh:``, ``with Mesh(...):``)."""
    spans = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.With):
            continue
        for item in node.items:
            expr = item.context_expr
            target = expr.func if isinstance(expr, ast.Call) else expr
            name = dotted_name(target) or ""
            if name.split(".")[-1].lower().endswith("mesh"):
                spans.append((node.lineno, node.end_lineno or node.lineno))
                break
    return spans


@rule("TL010", "implicit replication at mesh boundaries")
def check(module):
    # (a) shard_map with a mesh but no specs: every operand replicates
    for line, col, kwargs, params in shard_map_applications(module):
        if "mesh" in kwargs and ("in_specs" not in kwargs
                                 or "out_specs" not in kwargs):
            missing = [k for k in ("in_specs", "out_specs")
                       if k not in kwargs]
            yield Finding(
                "TL010", module.path, line, col,
                f"shard_map over a mesh with no {'/'.join(missing)} — "
                f"every unspecced operand is fully replicated on every "
                f"chip (declare a PartitionSpec per argument)")
            continue
        # (b) bare P() bound to a batch/sequence-scaling parameter.
        # Only in_specs: spec entries bind to the wrapped callable's
        # parameter NAMES, and outputs have no statically visible name
        # to judge batch-scaling by (out_specs axis-name checks live in
        # TL011; an all-replicated out_specs still surfaces through the
        # comm budget the program compiles to).
        entries = spec_entries(module, kwargs.get("in_specs"), line)
        if not entries or params is None:
            continue
        for i, entry in enumerate(entries):
            if not is_bare_partition_spec(entry):
                continue
            bound = params[i] if i < len(params) and len(entries) > 1 \
                else None
            if len(entries) == 1:
                # a single P() broadcasts to every argument
                bound = next((p for p in params
                              if is_batch_scaled_name(p)), None)
            if bound and is_batch_scaled_name(bound):
                yield Finding(
                    "TL010", module.path, entry.lineno,
                    entry.col_offset,
                    f"replicated spec P() on batch/sequence-scaling "
                    f"argument '{bound}' of a shard_map program — "
                    f"every chip holds (and moves) the full array; "
                    f"shard it or suppress with the reason it must "
                    f"replicate")

    # (a2) jit under a mesh context with no shardings anywhere
    spans = _mesh_with_blocks(module)
    if spans:
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and _callee_leaf(node.func) in ("jit", "pjit")):
                continue
            if any(lo <= node.lineno <= hi for lo, hi in spans):
                kw = {k.arg for k in node.keywords if k.arg}
                if not kw & {"in_shardings", "out_shardings"}:
                    yield Finding(
                        "TL010", module.path, node.lineno, node.col_offset,
                        f"jit inside a mesh context with neither "
                        f"in_shardings nor out_shardings — large inputs "
                        f"default to full replication across the mesh")

    # (b2) explicit replicated placement of a batch-scaling array
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Call) and _callee_leaf(node.func)
                in ("device_put", "with_sharding_constraint")):
            continue
        if len(node.args) < 2:
            continue
        target, sharding = node.args[0], node.args[1]
        has_bare = any(is_bare_partition_spec(sub)
                       for sub in ast.walk(sharding))
        tname = dotted_name(target)
        if has_bare and tname and is_batch_scaled_name(tname):
            yield Finding(
                "TL010", module.path, node.lineno, node.col_offset,
                f"batch/sequence-scaling array '{tname}' placed with the "
                f"replicated spec P() — every chip holds a full copy")
