"""Async tensor swapping between host RAM and NVMe.

TPU-native equivalent of reference ``runtime/swap_tensor/async_swapper.py``
(AsyncTensorSwapper) + the pinned-buffer management of
``csrc/aio/py_lib/deepspeed_pin_tensor.cpp``: a bounded pool of reusable host
buffers moved to/from disk by the native aio thread pool
(``csrc/aio/aio.cpp``), so swap I/O overlaps host compute (the C++ Adam step)
and steady-state host RAM stays at ``buffer_count × buffer_size`` regardless
of how much state lives on NVMe.
"""

import os

import numpy as np

from deepspeed_tpu.ops.aio import AsyncIOHandle, AIO_DEFAULT_BLOCK_SIZE
from deepspeed_tpu.utils.logging import logger

MIN_AIO_BYTES = 1024 * 1024
AIO_ALIGN = 4096


class SwapBuffer:
    """One reusable host staging buffer (fp32)."""

    def __init__(self, numel):
        self.data = np.zeros(numel, dtype=np.float32)
        self.in_flight = False

    def view(self, numel):
        assert numel <= self.data.size
        return self.data[:numel]


class AsyncTensorSwapper:
    """Move fp32 arrays host<->NVMe asynchronously with a buffer pool
    (reference ``async_swapper.py`` AsyncTensorSwapper.swap_out_tensors)."""

    def __init__(self, swap_dir, aio_handle=None, buffer_count=4,
                 buffer_size=None, block_size=AIO_DEFAULT_BLOCK_SIZE,
                 thread_count=4):
        self.swap_dir = swap_dir
        os.makedirs(swap_dir, exist_ok=True)
        self.handle = aio_handle or AsyncIOHandle(block_size=block_size,
                                                  thread_count=thread_count)
        self.buffer_count = buffer_count
        self.buffer_size = buffer_size
        self._buffers = []
        self._pending_writes = []

    def _get_buffer(self, numel):
        for b in self._buffers:
            if not b.in_flight and b.data.size >= numel:
                return b
        if len(self._buffers) < self.buffer_count:
            b = SwapBuffer(max(numel, self.buffer_size or 0))
            self._buffers.append(b)
            return b
        # pool exhausted: drain writes and retry
        self.synchronize_writes()
        for b in self._buffers:
            if not b.in_flight and b.data.size >= numel:
                return b
        b = SwapBuffer(max(numel, self.buffer_size or 0))
        self._buffers.append(b)
        return b

    def path_for(self, key):
        return os.path.join(self.swap_dir, f"{key}.swp")

    def swap_out(self, key, array):
        """Stage ``array`` into a pool buffer and start the async write."""
        flat = np.ascontiguousarray(array, dtype=np.float32).ravel()
        buf = self._get_buffer(flat.size)
        np.copyto(buf.view(flat.size), flat)
        buf.in_flight = True
        self.handle.async_pwrite(buf.view(flat.size), self.path_for(key))
        self._pending_writes.append(buf)
        return self.path_for(key)

    def synchronize_writes(self):
        if self._pending_writes:
            self.handle.wait()
            for b in self._pending_writes:
                b.in_flight = False
            self._pending_writes.clear()

    def swap_in(self, key, numel, out=None):
        """Synchronous read of a swapped tensor."""
        arr = out if out is not None else np.empty(numel, dtype=np.float32)
        self.handle.sync_pread(arr[:numel], self.path_for(key))
        return arr[:numel]

    def start_swap_in(self, key, numel):
        """Async prefetch into a pool buffer; returns the buffer. Call
        ``finish_swap_ins`` before touching it (pipeline_read path,
        reference ``pipelined_optimizer_swapper.py``)."""
        buf = self._get_buffer(numel)
        buf.in_flight = True
        self.handle.async_pread(buf.view(numel), self.path_for(key))
        return buf

    def finish_swap_ins(self):
        self.handle.wait()
        for b in self._buffers:
            b.in_flight = False

    def release(self):
        self.synchronize_writes()
        self._buffers.clear()
