"""Typed config base (analog of reference ``runtime/config_utils.py``
``DeepSpeedConfigModel``): pydantic models that tolerate unknown keys,
support deprecated aliases, and pretty-print."""

import json

from pydantic import BaseModel, ConfigDict


class DeepSpeedConfigModel(BaseModel):
    model_config = ConfigDict(extra="allow", populate_by_name=True,
                              arbitrary_types_allowed=True)

    def dump(self):
        return json.dumps(self.model_dump(), indent=2, default=str)


def get_scalar_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)
