"""Canonical tiny sharding-plan builders for the static collective-schedule
gate (``tools/lint/contract.py``).

Each builder constructs the SAME plan family the MULTICHIP dry-run exercises
(``__graft_entry__._run_dryrun_phases``: ZeRO-3 + tp + sp, MoE expert
parallelism, 1F1B pipeline x tp, MiCS hierarchical ZeRO) at toy sizes on the
8-virtual-device CPU mesh, and returns the jitted fused train step plus
concrete args — so the contract analyzer can compile it once and COUNT the
collective ops XLA actually scheduled.  Locking those counts in
``PROGRAMS.lock`` turns the dry-run's re-measured collective totals into a
static, diffable artifact: a sharding-plan change that silently adds an
all-gather (or drops the Ulysses all-to-all) fails the tier-1 gate with a
per-plan diff instead of surfacing as a multichip perf cliff.

Builders are self-contained and deterministic (fixed seeds, fixed shapes);
they require ``jax.device_count() >= 8`` (the tier-1 harness forces 8
virtual CPU devices; the ``ds_lint --contracts`` CLI does the same).
"""

import dataclasses
from typing import Any, Callable, Dict, Tuple

import numpy as np


@dataclasses.dataclass
class PlanProgram:
    """One sharding plan's fused step, ready to lower/compile.

    ``expect`` names the collectives the plan MUST schedule (sanity
    invariants, checked on top of the exact locked counts): e.g. ZeRO-3
    must all-gather params, a pipeline must collective-permute at stage
    boundaries.  ``reduction`` plans additionally require at least one of
    all-reduce / reduce-scatter (XLA picks per shape)."""
    name: str
    fn: Callable
    args: Tuple[Any, ...]
    mesh: Dict[str, int]
    expect: Tuple[str, ...] = ()
    reduction: bool = True


def _tiny_cfg(**over):
    from deepspeed_tpu.models.transformer import TransformerConfig
    base = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                max_seq_len=32, dtype="float32", use_flash_attention=False,
                remat=False)
    base.update(over)
    return TransformerConfig(**base)


def _fused_step_args(engine, batch):
    """(fused_step, args) for a lazily-initialized DeepSpeedEngine —
    the exact per-step program ``train_batch`` dispatches."""
    import jax
    import jax.numpy as jnp
    fused = engine._get_fused_step()
    args = (engine._params, engine._opt_state, engine._scaler_state,
            jnp.asarray(1e-3, jnp.float32), jnp.asarray(1, jnp.int32),
            engine._rng, jax.tree.map(jnp.asarray, batch))
    return fused, args


def zero3_tp_sp():
    """ZeRO-3 param sharding + Megatron tp=2 + Ulysses sp=2 over dp=2:
    param all-gathers, grad reduction, and the sp head/seq all-to-all."""
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import Transformer
    rng = np.random.default_rng(0)
    engine, *_ = deepspeed_tpu.initialize(
        model=Transformer(_tiny_cfg(max_seq_len=64)),
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 3},
                "gradient_clipping": 1.0,
                "tensor_parallel": {"tp_size": 2},
                "sequence_parallel": {"sp_size": 2}})
    batch = {"input_ids": rng.integers(0, 64, (2, 2, 64)).astype(np.int32)}
    micro = {"input_ids": batch["input_ids"][0]}
    engine._lazy_init((micro,), {})
    fn, args = _fused_step_args(engine, batch)
    return PlanProgram("parallel.zero3_tp_sp", fn, args,
                       mesh=dict(engine.mesh.shape),
                       expect=("all-gather", "all-to-all"))


def moe_ep():
    """Expert parallelism: experts sharded over ep=2, GShard
    dispatch/combine einsums, expert-data-parallel gradient semantics
    (ZeRO-2).  The dispatch is the einsum formulation
    (``moe/sharded_moe.py``), so GSPMD picks the collective: at this toy
    config XLA lowers it through all-gathers rather than an explicit
    all-to-all — the locked counts pin whichever schedule it chose, which
    is exactly what the gate is for (a strategy flip on a jax/XLA bump
    shows up as a readable diff, not a multichip surprise)."""
    import jax
    import jax.numpy as jnp
    import flax.linen as nn
    import deepspeed_tpu
    from deepspeed_tpu.moe.layer import MoE

    class MoELM(nn.Module):
        @nn.compact
        def __call__(self, batch):
            ids = batch["input_ids"]
            h = nn.Embed(64, 32, param_dtype=jnp.float32)(ids)
            y, aux, _ = MoE(hidden_size=32, num_experts=4, ep_size=2,
                            k=1, capacity_factor=2.0, dtype=jnp.float32,
                            name="moe")(h)
            h = h + y
            logits = nn.Dense(64)(h)
            tgt = jnp.pad(ids[:, 1:], ((0, 0), (0, 1)))
            ce = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits)
                                   * jax.nn.one_hot(tgt, 64), -1))
            return ce + 0.01 * aux

    rng = np.random.default_rng(1)
    engine, *_ = deepspeed_tpu.initialize(
        model=MoELM(),
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
                "moe": {"ep_size": 2},
                "zero_optimization": {"stage": 2}})
    batch = {"input_ids": rng.integers(0, 64, (1, 8, 16)).astype(np.int32)}
    micro = {"input_ids": batch["input_ids"][0]}
    engine._lazy_init((micro,), {})
    fn, args = _fused_step_args(engine, batch)
    return PlanProgram("parallel.moe_ep", fn, args,
                       mesh=dict(engine.mesh.shape))


def pipeline_1f1b():
    """pp=2 x tp=2 interleaved 1F1B: stage-boundary activations ride
    collective-permute; tp adds Megatron all-reduces."""
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models.pipeline_transformer import transformer_pipe
    rng = np.random.default_rng(2)
    pipe_module = transformer_pipe(_tiny_cfg(
        num_layers=4, scan_layers=False, pre_layer_norm=False,
        embed_proj_dim=32, tie_word_embeddings=True))
    engine, *_ = deepspeed_tpu.initialize(
        model=pipe_module,
        config={"train_micro_batch_size_per_gpu": 2,
                # M=4 > P=2 so the interleaved schedule's steady state
                # genuinely executes (same contract as the dry-run)
                "gradient_accumulation_steps": 4,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
                "tensor_parallel": {"tp_size": 2},
                "pipeline": {"stages": 2, "schedule": "1f1b"}})
    batch = jax.tree.map(
        jnp.asarray,
        {"input_ids": rng.integers(0, 64, (4, 2, 32)).astype(np.int32)})
    engine._lazy_init_pipe(batch)
    fused = engine._get_fused_step()
    args = (engine._params, engine._opt_state, engine._scaler_state,
            jnp.asarray(1e-4, jnp.float32), jnp.asarray(1, jnp.int32),
            engine._rng, batch)
    return PlanProgram("parallel.pipeline_1f1b", fused, args,
                       mesh=dict(engine.mesh.shape),
                       expect=("collective-permute",))


def mics():
    """MiCS hierarchical ZeRO-3 + tp=2: params shard within edp=2 groups
    (ICI-local all-gather) and grads reduce across mdp x edp."""
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import Transformer
    rng = np.random.default_rng(3)
    engine, *_ = deepspeed_tpu.initialize(
        model=Transformer(_tiny_cfg()),
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
                "bf16": {"enabled": True},
                "tensor_parallel": {"tp_size": 2},
                "zero_optimization": {"stage": 3, "mics_shard_size": 2}})
    dp_world = engine.topology.mdp * engine.topology.edp
    batch = {"input_ids": rng.integers(0, 64, (1, dp_world, 32))
             .astype(np.int32)}
    micro = {"input_ids": batch["input_ids"][0]}
    engine._lazy_init((micro,), {})
    fn, args = _fused_step_args(engine, batch)
    return PlanProgram("parallel.mics", fn, args,
                       mesh=dict(engine.mesh.shape),
                       expect=("all-gather",))


PLAN_BUILDERS = (zero3_tp_sp, moe_ep, pipeline_1f1b, mics)
