"""LR schedules — parity with reference ``runtime/lr_schedules.py``
(``LRRangeTest:258``, ``OneCycle:361``, ``WarmupLR:626``, ``WarmupDecayLR:715``)
plus cosine decay.  Schedules are pure functions of the step so the jitted
train step can take lr as a traced scalar; the class wrappers keep the
reference's stateful ``step()``/``get_lr()`` API for user code parity.
"""

import math

VALID_SCHEDULES = ["LRRangeTest", "OneCycle", "WarmupLR", "WarmupDecayLR",
                   "WarmupCosineLR", "CosineAnnealingLR"]


class _Schedule:
    """Stateful wrapper (reference schedules subclass torch lr_scheduler)."""

    def __init__(self, optimizer=None, last_batch_iteration=-1):
        self.optimizer = optimizer
        self.last_batch_iteration = last_batch_iteration

    def lr_at(self, step):
        raise NotImplementedError

    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration

    def get_lr(self):
        return [self.lr_at(max(self.last_batch_iteration, 0))]

    def get_last_lr(self):
        return self.get_lr()

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


class WarmupLR(_Schedule):
    """Linear warmup then constant (reference ``lr_schedules.py:626``)."""

    def __init__(self, optimizer=None, warmup_min_lr=0.0, warmup_max_lr=0.001,
                 warmup_num_steps=1000, warmup_type="log", last_batch_iteration=-1):
        super().__init__(optimizer, last_batch_iteration)
        self.warmup_min_lr = warmup_min_lr
        self.warmup_max_lr = warmup_max_lr
        self.warmup_num_steps = max(2, warmup_num_steps)
        self.warmup_type = warmup_type
        self.inverse_log_warm_up = 1.0 / math.log(self.warmup_num_steps)

    def _warmup_gamma(self, step):
        if step < self.warmup_num_steps:
            if self.warmup_type == "log":
                return self.inverse_log_warm_up * math.log(step + 1)
            return step / self.warmup_num_steps
        return 1.0

    def lr_at(self, step):
        g = self._warmup_gamma(step)
        return self.warmup_min_lr + (self.warmup_max_lr - self.warmup_min_lr) * g


class WarmupDecayLR(WarmupLR):
    """Warmup then linear decay to 0 at total_num_steps (reference ``:715``)."""

    def __init__(self, optimizer=None, total_num_steps=10000, warmup_min_lr=0.0,
                 warmup_max_lr=0.001, warmup_num_steps=1000, warmup_type="log",
                 last_batch_iteration=-1):
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr, warmup_num_steps,
                         warmup_type, last_batch_iteration)
        self.total_num_steps = total_num_steps

    def lr_at(self, step):
        if step < self.warmup_num_steps:
            return super().lr_at(step)
        decay = max(0.0, (self.total_num_steps - step) /
                    max(1, self.total_num_steps - self.warmup_num_steps))
        return self.warmup_max_lr * decay


class WarmupCosineLR(WarmupLR):
    """TPU-native addition: warmup + cosine decay to min_lr."""

    def __init__(self, optimizer=None, total_num_steps=10000, warmup_min_lr=0.0,
                 warmup_max_lr=0.001, warmup_num_steps=1000, cos_min_ratio=0.0,
                 warmup_type="linear", last_batch_iteration=-1):
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr, warmup_num_steps,
                         warmup_type, last_batch_iteration)
        self.total_num_steps = total_num_steps
        self.cos_min_ratio = cos_min_ratio

    def lr_at(self, step):
        if step < self.warmup_num_steps:
            return super().lr_at(step)
        progress = min(1.0, (step - self.warmup_num_steps) /
                       max(1, self.total_num_steps - self.warmup_num_steps))
        cos = 0.5 * (1 + math.cos(math.pi * progress))
        floor = self.warmup_max_lr * self.cos_min_ratio
        return floor + (self.warmup_max_lr - floor) * cos


CosineAnnealingLR = WarmupCosineLR


class LRRangeTest(_Schedule):
    """LR range sweep (reference ``lr_schedules.py:258``)."""

    def __init__(self, optimizer=None, lr_range_test_min_lr=1e-3,
                 lr_range_test_step_size=2000, lr_range_test_step_rate=1.0,
                 lr_range_test_staircase=False, last_batch_iteration=-1):
        super().__init__(optimizer, last_batch_iteration)
        self.min_lr = lr_range_test_min_lr
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase

    def lr_at(self, step):
        interval = step // self.step_size if self.staircase else step / self.step_size
        return self.min_lr * (1 + self.step_rate * interval)


class OneCycle(_Schedule):
    """1-cycle policy (reference ``lr_schedules.py:361``): lr ramps
    first_step_size up then back down, then decays; momentum cycles inversely."""

    def __init__(self, optimizer=None, cycle_min_lr=1e-4, cycle_max_lr=1e-3,
                 decay_lr_rate=0.0, cycle_first_step_size=2000,
                 cycle_second_step_size=None, cycle_first_stair_count=0,
                 cycle_second_stair_count=None, decay_step_size=0,
                 cycle_momentum=True, cycle_min_mom=0.85, cycle_max_mom=0.99,
                 decay_mom_rate=0.0, last_batch_iteration=-1):
        super().__init__(optimizer, last_batch_iteration)
        self.cycle_min_lr = cycle_min_lr
        self.cycle_max_lr = cycle_max_lr
        self.decay_lr_rate = decay_lr_rate
        self.first = cycle_first_step_size
        self.second = cycle_second_step_size or cycle_first_step_size
        self.decay_step_size = decay_step_size
        self.cycle_momentum = cycle_momentum
        self.cycle_min_mom = cycle_min_mom
        self.cycle_max_mom = cycle_max_mom
        self.decay_mom_rate = decay_mom_rate

    def lr_at(self, step):
        total = self.first + self.second
        if step <= self.first:
            frac = step / self.first
            return self.cycle_min_lr + (self.cycle_max_lr - self.cycle_min_lr) * frac
        if step <= total:
            frac = (step - self.first) / self.second
            return self.cycle_max_lr - (self.cycle_max_lr - self.cycle_min_lr) * frac
        post = step - total
        if self.decay_step_size > 0:
            return self.cycle_min_lr / (1 + self.decay_lr_rate * (post // self.decay_step_size))
        return self.cycle_min_lr

    def mom_at(self, step):
        total = self.first + self.second
        if step <= self.first:
            frac = step / self.first
            return self.cycle_max_mom - (self.cycle_max_mom - self.cycle_min_mom) * frac
        if step <= total:
            frac = (step - self.first) / self.second
            return self.cycle_min_mom + (self.cycle_max_mom - self.cycle_min_mom) * frac
        return self.cycle_max_mom


SCHEDULE_REGISTRY = {
    "WarmupLR": WarmupLR,
    "WarmupDecayLR": WarmupDecayLR,
    "WarmupCosineLR": WarmupCosineLR,
    "CosineAnnealingLR": WarmupCosineLR,
    "LRRangeTest": LRRangeTest,
    "OneCycle": OneCycle,
}


def build_lr_scheduler(sched_config, optimizer=None):
    """Map config ``scheduler`` block to an instance (reference
    ``engine.py:842 _configure_lr_scheduler``)."""
    if sched_config is None or sched_config.type is None:
        return None
    cls = SCHEDULE_REGISTRY.get(sched_config.type)
    if cls is None:
        raise ValueError(f"unknown scheduler {sched_config.type}; valid: {VALID_SCHEDULES}")
    return cls(optimizer=optimizer, **sched_config.params)
