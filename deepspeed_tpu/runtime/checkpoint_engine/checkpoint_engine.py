"""Checkpoint I/O engines.

Parity with reference ``runtime/checkpoint_engine/checkpoint_engine.py:9-28``
(``CheckpointEngine`` ABC: create/save/load/commit) — the Orbax engine plays
both the Torch role (synchronous) and the Nebula role (async tiered save)
since Orbax natively does async, sharded, resharding-on-load checkpoints.
"""

import os
import pickle
from abc import ABC, abstractmethod

from deepspeed_tpu.runtime.fault import inject
from deepspeed_tpu.runtime.fault.atomic import atomic_write_bytes
from deepspeed_tpu.utils.logging import logger


class CheckpointEngine(ABC):
    """create/save/load/commit protocol.  ``save`` takes the device-array
    pytree and a picklable metadata dict separately — array leaves go through
    the sharded writer, metadata through pickle."""

    def __init__(self, config_params=None):
        self.config_params = config_params

    def create(self, tag):
        logger.info(f"[ckpt] checkpoint tag {tag} begin")

    @abstractmethod
    def save(self, arrays, meta, path: str):
        ...

    @abstractmethod
    def load(self, path: str, abstract_arrays=None):
        """Returns (arrays, meta).  ``abstract_arrays`` (ShapeDtypeStruct tree
        with shardings) enables resharding-on-load."""
        ...

    @abstractmethod
    def commit(self, tag):
        ...


class OrbaxCheckpointEngine(CheckpointEngine):
    """Sharded, optionally async save/restore of jax.Array pytrees.

    Restoring onto a different mesh/sharding reshapes automatically — this
    single mechanism covers the reference's ZeRO-shard merging
    (``zero_to_fp32.py:459``), universal-checkpoint resharding
    (``deepspeed/checkpoint/``), and elastic world-size changes.
    """

    def __init__(self, config_params=None, use_async=False):
        super().__init__(config_params)
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.use_async = use_async
        self._ckptr = None
        # async mode: (path, pickled meta) pairs whose durability is
        # deferred to commit() — metadata must never land before the
        # array shards it describes (see save())
        self._pending_meta = []

    def _checkpointer(self):
        if self._ckptr is None:
            self._ckptr = self._ocp.StandardCheckpointer()
        return self._ckptr

    def save(self, arrays, meta, path):
        path = os.path.abspath(path)
        if arrays is not None:
            ckptr = self._checkpointer()
            ckptr.save(os.path.join(path, "arrays"), arrays, force=True)
            if not self.use_async:
                ckptr.wait_until_finished()
        inject.fire("ckpt.arrays_write", path=path)
        os.makedirs(path, exist_ok=True)
        meta_bytes = pickle.dumps(meta)
        if self.use_async and arrays is not None:
            # async-save ordering: the array shards are NOT yet durable
            # here.  Writing meta.pkl now would let a crash between the
            # two leave a metadata-complete but data-incomplete
            # checkpoint — durability is established only at commit(),
            # after wait_until_finished()
            self._pending_meta.append((path, meta_bytes))
            return
        # temp-file + os.replace: a crash mid-write must never leave a
        # truncated meta.pkl shadowing the real one
        atomic_write_bytes(os.path.join(path, "meta.pkl"), meta_bytes)

    def load(self, path, abstract_arrays=None):
        path = os.path.abspath(path)
        meta = {}
        meta_path = os.path.join(path, "meta.pkl")
        if os.path.exists(meta_path):
            with open(meta_path, "rb") as f:
                meta = pickle.load(f)
        arrays = None
        arrays_path = os.path.join(path, "arrays")
        if os.path.isdir(arrays_path):
            arrays = self._checkpointer().restore(arrays_path, abstract_arrays)
        return arrays, meta

    def metadata(self, path):
        """Shapes/dtypes of the saved arrays (no data read) — lets a FRESH
        engine build device-agnostic restore targets, so a checkpoint saved
        by a different process/device topology (e.g. 2 hosts × 4 chips)
        loads on the current one (1 host × 8): Orbax otherwise restores
        onto the devices recorded at save time."""
        arrays_path = os.path.join(os.path.abspath(path), "arrays")
        if not os.path.isdir(arrays_path):
            return None
        md = self._checkpointer().metadata(arrays_path)
        # unwrap StepMetadata/TreeMetadata to the plain ArrayMetadata pytree
        item = getattr(md, "item_metadata", md)
        return getattr(item, "tree", item)

    def commit(self, tag):
        if self._ckptr is not None:
            self._ckptr.wait_until_finished()
        # arrays are durable now — publish the deferred metadata (async
        # mode; empty list in sync mode).  Entries whose staging dir has
        # vanished belong to an earlier save that aborted and was GC'd:
        # drop them with a warning rather than failing THIS commit
        pending, self._pending_meta = self._pending_meta, []
        for path, meta_bytes in pending:
            if not os.path.isdir(path):
                logger.warning(f"[ckpt] dropping deferred metadata for "
                               f"vanished save at {path} (aborted save?)")
                continue
            atomic_write_bytes(os.path.join(path, "meta.pkl"), meta_bytes)
        logger.info(f"[ckpt] checkpoint tag {tag} committed")
        return True


# Parity alias: the reference's torch engine (synchronous save) — same class,
# synchronous mode.
class TorchCheckpointEngine(OrbaxCheckpointEngine):

    def __init__(self, config_params=None):
        super().__init__(config_params, use_async=False)


# Parity alias: Nebula async tiered save → orbax async mode.
class NebulaCheckpointEngine(OrbaxCheckpointEngine):

    def __init__(self, config_params=None):
        super().__init__(config_params, use_async=True)
