"""TL010 positive fixture — implicit replication at mesh boundaries.

Every construct here should be flagged: unspecced shard_maps, bare P()
specs on batch/sequence-scaling arguments (call, decorator, and
spec-variable forms), a sharding-free jit under a mesh context, and
explicit replicated placements of batch-scaling arrays."""
import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

mesh = Mesh(jax.devices(), ("tp",))


def body(x, w):
    return x @ w


# (a) mesh but no specs at all: every operand replicates
smap_unspecced = shard_map(body, mesh=mesh)

# (a) in_specs without out_specs: the OUTPUT replicates
smap_half = shard_map(body, mesh=mesh, in_specs=(P("tp"), P(None, "tp")))


# (b) bare P() bound to the batch-scaling first argument at a call site
smap_replicated = shard_map(body, mesh=mesh,
                            in_specs=(P(), P(None, "tp")),
                            out_specs=P())


# (b) decorator form: the hidden activations replicate
@functools.partial(shard_map, mesh=mesh,
                   in_specs=(P(), P("tp")), out_specs=P())
def region(hidden, w):
    return hidden * w


def stage(acts, params):
    return acts @ params


# (b) spec-variable indirection: same replication, one assignment away
in_specs = (P(), P(None, "tp"))
smap_indirect = shard_map(stage, mesh=mesh, in_specs=in_specs,
                          out_specs=P())


def run_under_mesh(batch):
    # (a2) jit in a mesh context with no shardings anywhere
    with mesh:
        step = jax.jit(lambda b: b * 2)
        return step(batch)


def place(input_ids, logits):
    # (b2) replicated placement of batch-scaling arrays
    rep = NamedSharding(mesh, P())
    ids = jax.device_put(input_ids, rep)
    out = jax.lax.with_sharding_constraint(logits, NamedSharding(mesh, P()))
    return ids, out
