"""Topology tests — analog of reference ``tests/unit/runtime/pipe/test_topology.py``."""

import pytest

import jax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import (
    ParallelTopology, initialize_topology, get_topology, AXIS_ORDER, DP_AXES)


def test_default_topology_all_dp():
    topo = initialize_topology()
    assert topo.world_size == 8
    assert topo.dp == 8
    assert topo.tp == topo.pp == topo.sp == topo.ep == 1
    assert topo.mesh.axis_names == AXIS_ORDER


def test_2d_topology():
    topo = initialize_topology(tp=2)
    assert topo.dp == 4
    assert topo.get_model_parallel_world_size() == 2
    assert topo.get_data_parallel_world_size() == 4


def test_3d_topology():
    topo = initialize_topology(tp=2, pp=2)
    assert topo.dp == 2
    assert topo.world_size == 8


def test_expert_topology():
    topo = initialize_topology(ep=4)
    assert topo.dp == 8
    assert topo.edp == 2
    assert topo.axis_size("ep") == 4


def test_sequence_topology():
    topo = initialize_topology(sp=2, tp=2)
    assert topo.sp == 2
    assert topo.dp == 2


def test_invalid_topology_raises():
    with pytest.raises(ValueError):
        ParallelTopology(dp=16, tp=2, devices=jax.devices())


def test_ep_must_divide_dp():
    with pytest.raises(ValueError):
        ParallelTopology(dp=4, ep=3, devices=jax.devices())


def test_batch_spec():
    topo = initialize_topology()
    assert topo.data_spec() == P(DP_AXES)
